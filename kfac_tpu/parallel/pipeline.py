"""Pipeline parallelism (GPipe schedule) with K-FAC, SPMD-style.

Capability parity with the reference's GPT-NeoX pipeline support
(kfac/gpt_neox/: DeepSpeed PipelineModule topology, factors assigned among
pipe-parallel peers, hardwired MEM-OPT — gpt_neox/assignment.py:95-130),
re-designed for a TPU mesh:

- Stage parameters are STACKED on a leading stage axis and sharded over the
  ``pipe`` mesh axis; every device runs the same traced program on its
  stage slice (no per-rank module partitioning).
- The schedule is a ``lax.scan`` over ticks: each tick applies the local
  stage to the activation in flight and ``ppermute``s it to the next stage.
  Microbatches enter at stage 0 and exit at the last stage
  (fill/drain bubbles compute on zeros and are masked out of statistics and
  outputs).
- K-FAC curvature capture cannot use the global interceptor-closure trick
  here (stats live inside the shard_map/scan trace), so the pipeline body
  accumulates A statistics in the scan carry and routes G statistics out
  through custom_vjp g-taps whose dummies are shard_map arguments with a
  stage-sharded leading axis.
- Second-order state for stage layers keeps that stage axis and stays
  sharded over ``pipe``: each stage eigendecomposes and preconditions only
  its own layers — the reference's MEM-OPT-among-pipe-peers placement,
  with zero inverse traffic across stages.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kfac_tpu.layers import capture as capture_lib
from kfac_tpu.layers import registry as registry_lib
from kfac_tpu.models import transformer as transformer_lib
from kfac_tpu.ops import factors as factors_lib
from kfac_tpu.ops import losses as losses_lib
from kfac_tpu.parallel import mesh as mesh_lib
from kfac_tpu.preconditioner import KFACPreconditioner, _resolve

PIPE_AXIS = mesh_lib.PIPE_AXIS


class StageBlocks(nn.Module):
    """A pipeline stage: ``blocks_per_stage`` transformer blocks."""

    blocks_per_stage: int
    num_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for i in range(self.blocks_per_stage):
            x = transformer_lib.Block(
                self.num_heads, self.mlp_ratio, dtype=self.dtype,
                name=f'block{i}',
            )(x)
        return x


@dataclasses.dataclass
class PipelinedLM:
    """Decoder LM with its blocks pipelined over a ``pipe`` mesh axis.

    Embedding and the output head run replicated outside the pipeline (they
    are a small fraction of compute); the block stack runs under the GPipe
    schedule. ``n_microbatches`` must divide the batch.

    The per-stage module defaults to :class:`StageBlocks` (transformer
    blocks) but ANY flax module mapping ``(B, S, d_model) -> (B, S,
    d_model)`` can be pipelined via ``stage_module`` — the counterpart of
    the reference wrapping arbitrary DeepSpeed ``PipelineModule``s
    (kfac/gpt_neox/preconditioner.py:161-165). The K-FAC registry, capture
    taps, TP sharding rules, and both schedules are derived from the module
    itself, so no other knob changes.
    """

    mesh: Mesh
    vocab_size: int
    d_model: int
    num_heads: int
    num_layers: int
    n_microbatches: int = 4
    mlp_ratio: int = 4
    max_len: int = 2048
    dtype: Any = jnp.float32
    # Rematerialize each stage application in the backward pass: residual
    # memory drops from every internal activation of every tick to just the
    # per-tick stage inputs — the memory profile 1F1B buys over GPipe,
    # traded for ~1/3 extra stage FLOPs instead of a hand-scheduled
    # backward (XLA recomputes inside the scan's transpose).
    remat: bool = True
    # 'gpipe': forward scan + autodiff transpose (residual memory grows
    # with n_microbatches: the scan saves one stage input per tick).
    # '1f1b': ONE combined scan computes forward and backward slots per
    # tick — stage s runs F of microbatch (t - s) and B of microbatch
    # (t - (2S-2-s)); the last stage computes head+loss+cotangent in-tick
    # so backward drains while the pipe is still filling. STAGE residual
    # memory is a (2S-1)-slot ring regardless of n_microbatches — the 1F1B
    # memory bound (vs DeepSpeed's PipelineEngine schedule the reference
    # rides, kfac/gpt_neox/preconditioner.py:70-73); the O(M) buffers that
    # remain are the model's own input feed and the stage-0 input-cotangent
    # collection for the embed backward (GPipe carries both too, PLUS one
    # saved stage input per tick). The bubble fraction (2S-2)/(M+2S-2) can
    # therefore be amortized with as many microbatches as the batch
    # affords. Loss, parameter grads, AND the K-FAC A/G statistics come
    # out of the same scan: B slots recompute the stage forward under an
    # explicit jax.vjp with the capture interceptor + g-taps attached.
    schedule: str = 'gpipe'
    # regex patterns excluding stage layers from K-FAC registration (same
    # semantics as register_model's skip_layers; the reference's LM example
    # skips attention projections this way)
    skip_layers: tuple[str, ...] | None = None
    # Tensor-parallel kinds for stage layers (layer-name regex -> 'column' /
    # 'row' / 'replicated'), used when the mesh has a model axis of size >1.
    # Defaults cover StageBlocks' Megatron pairing: qkv/mlp_up
    # column-parallel, out_proj/mlp_down row-parallel — the reference's
    # ColumnParallelLinear/RowParallelLinear assignment
    # (kfac/gpt_neox/preconditioner.py:189-191).
    tp_overrides: tuple[tuple[str, str], ...] = (
        (r'.*(q_proj|k_proj|v_proj|mlp_up)', 'column'),
        (r'.*(out_proj|mlp_down)', 'row'),
    )
    # Custom per-stage module: any flax module (B, S, d_model) ->
    # (B, S, d_model). None selects StageBlocks(num_layers / n_stages
    # transformer blocks). With a custom module, num_layers/mlp_ratio are
    # ignored for stage construction (num_heads only feeds StageBlocks).
    stage_module: nn.Module | None = None

    def __post_init__(self) -> None:
        import warnings as _warnings

        from kfac_tpu.warnings import ExperimentalFeatureWarning

        _warnings.warn(
            'pipeline-parallel K-FAC is experimental (the reference flags '
            'its pipeline support the same way)',
            ExperimentalFeatureWarning,
            stacklevel=2,
        )
        if self.schedule not in ('gpipe', '1f1b', 'interleaved'):
            raise ValueError(
                f"unknown schedule {self.schedule!r}: 'gpipe', '1f1b', or "
                f"'interleaved'"
            )
        if self.schedule == 'interleaved' and not self._executes_interleaved():
            raise ValueError(
                "the 'interleaved' schedule requires "
                'InterleavedPipelinedLM (parallel/interleaved_scan.py)'
            )
        # logical stage count: pipe ranks x chunks per rank (1 for this
        # class; InterleavedPipelinedLM overrides _chunks_per_rank so the
        # stage module/registry below are built ONCE with the right count)
        self.n_stages = int(self.mesh.shape[PIPE_AXIS]) * (
            self._chunks_per_rank()
        )
        # Every non-pipe, non-model mesh axis is a data-parallel axis: the
        # batch shards over them and factor statistics reduce over them (the
        # reference's factor allreduce over the DP group,
        # kfac/gpt_neox/layer.py:61-93). The model axis (TP) is NOT a data
        # axis: the schedule leaves it automatic — shard_map runs manual
        # over pipe+data only — so GSPMD inserts the Megatron all-reduces
        # inside each stage application (the reference's 3D composition,
        # kfac/gpt_neox/preconditioner.py:70-73,189-191).
        self.data_axes = tuple(
            ax
            for ax in self.mesh.axis_names
            if ax not in (PIPE_AXIS, mesh_lib.MODEL_AXIS)
        )
        self.tp = int(dict(self.mesh.shape).get(mesh_lib.MODEL_AXIS, 1))
        self._manual = frozenset((PIPE_AXIS,) + self.data_axes)
        self.embed = nn.Embed(self.vocab_size, self.d_model, name='embed')
        if self.stage_module is not None:
            self.stage = self.stage_module
        else:
            if self.num_layers % self.n_stages != 0:
                raise ValueError('num_layers must divide evenly into stages')
            self.blocks_per_stage = self.num_layers // self.n_stages
            self.stage = StageBlocks(
                self.blocks_per_stage, self.num_heads, self.mlp_ratio,
                self.dtype,
            )
        self.head = nn.Dense(self.vocab_size, use_bias=False, name='lm_head')
        self.ln_f = nn.LayerNorm(dtype=jnp.float32, name='ln_f')
        # Registry of one stage's K-FAC layers (shapes identical per stage).
        x = jnp.zeros((1, 8, self.d_model), self.dtype)
        out_shape = jax.eval_shape(
            lambda v: self.stage.init_with_output(
                jax.random.PRNGKey(0), v
            )[0],
            x,
        ).shape
        if out_shape != x.shape:
            raise ValueError(
                f'stage module must map (B, S, {self.d_model}) to itself '
                f'(pipeline stages chain), got output shape {out_shape}'
            )
        self.stage_registry = registry_lib.register_model(
            self.stage, x, skip_layers=list(self.skip_layers or []),
        )
        # the in-schedule capture averages by invocation count with no
        # weights path; a weighted (routed) helper would come out of
        # g_factor_for_sum pre-scaled by its live fraction and silently
        # mis-scale G vs A — reject rather than mis-precondition
        weighted = [
            n for n, h in self.stage_registry.layers.items()
            if getattr(h, 'weighted', False)
        ]
        if weighted:
            raise NotImplementedError(
                f'routed (traffic-weighted) layers {weighted} are not '
                'supported inside pipeline stages; the pipeline capture '
                'keeps equal-weight averaging (see '
                'cov.routed_linear_a_factor exactness notes)'
            )
        self._gtaps = {
            name: capture_lib._make_gtap(h)
            for name, h in self.stage_registry.layers.items()
        }

    def _chunks_per_rank(self) -> int:
        """Model chunks per pipeline rank (1 here; the interleaved
        subclass returns ``virtual_chunks``)."""
        return 1

    def _executes_interleaved(self) -> bool:
        """Whether this class runs the single-slot interleaved scan —
        NOT the same as ``_chunks_per_rank() > 1``: an
        InterleavedPipelinedLM with ``virtual_chunks=1`` is valid and
        still executes the interleaved scan."""
        return False

    def _make_head_loss(self, total_tokens: float):
        """Summed-token-NLL/total_tokens closure shared by the combined
        1F1B and single-slot interleaved bodies (the fused NLL keeps the
        head vocab-parallel when the kernel is sharded over the automatic
        model axis — ops/losses.vocab_parallel_nll)."""

        def head_loss(y, hp, lp, tgt):
            yl = self.ln_f.apply({'params': lp}, y.astype(jnp.float32))
            logits = self.head.apply({'params': hp}, yl)
            return jnp.sum(losses_lib.vocab_parallel_nll(logits, tgt)) / (
                total_tokens
            )

        return head_loss

    @staticmethod
    def _zeros_like_vary(all_axes):
        """Fresh zeros pcast varying over ``all_axes`` (scan carries and
        cond branches must agree with the inputs' vma types)."""
        return lambda t: jax.tree_util.tree_map(
            lambda v: jax.lax.pcast(
                jnp.zeros(v.shape, v.dtype), all_axes, to='varying'
            ),
            t,
        )

    # ------------------------------------------------------------ params

    def init(self, rng: jax.Array) -> dict[str, Any]:
        r_embed, r_stage, r_head, r_pos = jax.random.split(rng, 4)
        dummy_tok = jnp.zeros((1, 8), jnp.int32)
        dummy_x = jnp.zeros((1, 8, self.d_model), self.dtype)
        stage_rngs = jax.random.split(r_stage, self.n_stages)
        stage_params = jax.vmap(
            lambda r: self.stage.init(r, dummy_x)['params']
        )(stage_rngs)
        params = {
            'embed': self.embed.init(r_embed, dummy_tok)['params'],
            'pos_embed': jax.random.normal(
                r_pos, (self.max_len, self.d_model)
            ) * 0.02,
            'stages': stage_params,  # every leaf has leading dim n_stages
            'ln_f': self.ln_f.init(
                jax.random.PRNGKey(0), dummy_x.astype(jnp.float32)
            )['params'],
            'head': self.head.init(r_head, dummy_x.astype(jnp.float32))['params'],
        }
        # place stage params sharded over the pipe axis; with TP active the
        # feature dims additionally shard over the model axis per the
        # registry-derived Megatron kinds
        if self.tp > 1:
            from kfac_tpu.parallel import tensor_parallel

            tp_specs = tensor_parallel.registry_param_specs(
                params['stages'],
                self.stage_registry,
                overrides=self.tp_overrides,
                warn_unmatched=False,
            )
            if not any(
                mesh_lib.MODEL_AXIS in s
                for s in jax.tree_util.tree_leaves(
                    tp_specs, is_leaf=lambda x: isinstance(x, P)
                )
            ):
                import warnings as _warnings

                _warnings.warn(
                    f'model axis has {self.tp} shards but NO stage '
                    'parameter matched a tensor-parallel rule — all stage '
                    'weights are fully replicated over the model axis. '
                    'Pass tp_overrides mapping your stage layer names to '
                    "'column'/'row' (square layers are never sharded by "
                    'the shape heuristic).',
                    tensor_parallel.UnshardedParamWarning,
                    stacklevel=2,
                )
            params['stages'] = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, P(PIPE_AXIS, *s))
                ),
                params['stages'],
                tp_specs,
            )
            # Vocab-parallel LM head (Megatron's VocabParallelEmbedding
            # pairing, which the reference rides through its GPT-NeoX
            # integration): the (d, V) kernel shards V over the model axis
            # per the SAME rule table the dense TransformerLM uses
            # (TRANSFORMER_TP_RULES '.*lm_head/kernel'), so the two paths
            # cannot drift apart. The model axis is automatic in both
            # schedules' shard_maps, so GSPMD keeps the head matmul and the
            # fused NLL's softmax reductions (ops/losses.vocab_parallel_nll)
            # at 1/tp per device instead of replicating the full d x V
            # matmul per microbatch.
            params['head'] = tensor_parallel.shard_params(
                {'lm_head': params['head']}, self.mesh
            )['lm_head']
        else:
            stage_sharding = NamedSharding(self.mesh, P(PIPE_AXIS))
            params['stages'] = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, stage_sharding), params['stages']
            )
        return params

    # ----------------------------------------------------------- pipeline

    def _stage_apply_captured(self, sp, gst, x, valid):
        """One stage application with curvature taps attached.

        Returns ``(y, tick_a)``: the stage output with g-taps wrapped
        around every registered layer (their vjp emits G factors into the
        ``gst`` dummies' cotangents) and the per-layer A factors of this
        application, masked by ``valid``. Shared by the GPipe forward body
        and the 1F1B backward-slot recompute so capture semantics cannot
        diverge between schedules.
        """
        registry = self.stage_registry
        tick_a: dict[str, jax.Array] = {}

        def interceptor(next_fun, iargs, ikwargs, context):
            mod = context.module
            if context.method_name != '__call__' or not iargs:
                return next_fun(*iargs, **ikwargs)
            name = registry_lib.path_name(mod.path)
            helper = registry.layers.get(name)
            if helper is None:
                return next_fun(*iargs, **ikwargs)
            a = jax.lax.stop_gradient(iargs[0])
            tick_a[name] = tick_a.get(name, 0.0) + (
                helper.get_a_factor(a) * valid
            )
            y = next_fun(*iargs, **ikwargs)
            # masked ticks contribute zero: their outputs never reach the
            # loss, so cotangents — and G contributions — are exactly zero
            return self._gtaps[name](y, gst[name])

        with nn.intercept_methods(interceptor):
            y = self.stage.apply({'params': sp}, x)
        return y, tick_a

    def _validate_batch(self, b: int) -> int:
        """Check batch divisibility; returns the data-parallel world."""
        m = self.n_microbatches
        if b % m != 0:
            raise ValueError(f'batch {b} not divisible by {m} microbatches')
        dp = 1
        for ax in self.data_axes:
            dp *= int(self.mesh.shape[ax])
        if (b // m) % dp != 0:
            raise ValueError(
                f'per-microbatch batch {b // m} not divisible by the '
                f'data-parallel world {dp}'
            )
        return dp

    def _pipeline_body(self, stage_params, x_feed, gstats):
        """shard_map body: local stage over all ticks of the schedule.

        Args (local views):
            stage_params: this stage's params (leading dim 1).
            x_feed: (M, B_m, S, D) microbatch activations (replicated).
            gstats: zero g-tap dummies, leading dim 1 (this stage's slice).
        Returns (local views):
            out: (M, B_m, S, D) last-stage outputs (valid on last stage).
            a_stats: dict name -> (1, da, da) summed A statistics.
            counts: (1,) number of real microbatches processed.
        """
        sp = jax.tree_util.tree_map(lambda x: x[0], stage_params)
        gst = {k: v[0] for k, v in gstats.items()}
        if self.data_axes:
            # Stage params/g-dummies are replicated over the data axes and
            # the batch feed over pipe; broadcast all to the full varying
            # set so the schedule mixes them freely. The pcast over the data
            # axes transposes to a psum — exactly the DP reduction for
            # stage gradients and G statistics.
            sp = jax.tree_util.tree_map(
                lambda v: jax.lax.pcast(v, self.data_axes, to='varying'), sp
            )
            gst = {
                k: jax.lax.pcast(v, self.data_axes, to='varying')
                for k, v in gst.items()
            }
            x_feed = jax.lax.pcast(x_feed, (PIPE_AXIS,), to='varying')
        stage_idx = jax.lax.axis_index(PIPE_AXIS)
        if self.data_axes:
            stage_idx = jax.lax.pcast(
                stage_idx, self.data_axes, to='varying'
            )
        n = self.n_stages
        m = self.n_microbatches
        ticks = m + n - 1
        b_m, s, d = x_feed.shape[1:]
        perm = [(j, (j + 1) % n) for j in range(n)]
        registry = self.stage_registry

        def apply_stage(x, valid):
            return self._stage_apply_captured(sp, gst, x, valid)

        if self.remat:
            apply_stage = jax.checkpoint(apply_stage)

        zero_a = {
            name: jnp.zeros(h.a_factor_shape, jnp.float32)
            for name, h in registry.layers.items()
        }

        def tick(carry, t):
            x_in, a_acc, n_valid = carry
            # stage 0 ingests microbatch t (zeros once the feed is drained)
            feed_mask = (t < m).astype(x_feed.dtype)
            feed = feed_mask * jax.lax.dynamic_index_in_dim(
                x_feed, jnp.minimum(t, m - 1), keepdims=False
            )
            x_in = jnp.where(stage_idx == 0, feed, x_in)
            # my microbatch index at this tick; valid while in [0, m)
            mb = t - stage_idx
            valid = jnp.logical_and(mb >= 0, mb < m)
            validf = valid.astype(jnp.float32)
            y, tick_a = apply_stage(x_in, validf)
            a_acc = {k: a_acc[k] + tick_a[k] for k in a_acc}
            n_valid = n_valid + validf
            # keep only real outputs; bubbles propagate zeros
            y = y * validf.astype(y.dtype)
            x_next = jax.lax.ppermute(y, PIPE_AXIS, perm)
            return (x_next, a_acc, n_valid), (y, mb)

        all_axes = (PIPE_AXIS,) + self.data_axes
        x0 = jax.lax.pcast(
            jnp.zeros((b_m, s, d), self.dtype), all_axes, to='varying'
        )
        zero_a = jax.tree_util.tree_map(
            lambda v: jax.lax.pcast(v, all_axes, to='varying'), zero_a
        )
        n_valid0 = jax.lax.pcast(
            jnp.zeros((), jnp.float32), all_axes, to='varying'
        )
        (x_last, a_acc, n_valid), (ys, mbs) = jax.lax.scan(
            tick, (x0, zero_a, n_valid0), jnp.arange(ticks)
        )
        # gather this stage's outputs into microbatch order (only the last
        # stage's are real; others zero)
        out = jax.lax.pcast(
            jnp.zeros((m, b_m, s, d), self.dtype), all_axes, to='varying'
        )
        is_last = (stage_idx == n - 1).astype(self.dtype)

        def collect(out, ty):
            t, y, mb = ty
            mb_c = jnp.clip(mb, 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(out, mb_c, keepdims=False)
            upd = jnp.where((mb >= 0) & (mb < m), y * is_last, cur)
            return jax.lax.dynamic_update_index_in_dim(out, upd, mb_c, 0), None

        out, _ = jax.lax.scan(
            collect, out, (jnp.arange(ticks), ys, mbs)
        )
        # only the last stage holds real outputs (zeros elsewhere): the psum
        # is the broadcast from the final stage to the world
        out = jax.lax.psum(out, PIPE_AXIS)
        if self.data_axes:
            # DP factor reduction: sum A stats and tick counts over the data
            # axes; loss_and_stats divides by the summed counts, yielding
            # the global-batch mean (per-tick factors normalize by local
            # rows, so the division is exact for any dp size).
            a_acc = {
                k: jax.lax.psum(v, self.data_axes) for k, v in a_acc.items()
            }
            n_valid = jax.lax.psum(n_valid, self.data_axes)
        a_stats = {k: v[None] for k, v in a_acc.items()}
        return out, a_stats, n_valid[None]

    def _embed(self, params, tokens):
        x = self.embed.apply({'params': params['embed']}, tokens)
        pos = params['pos_embed'][: tokens.shape[-1]]
        return (x + pos).astype(self.dtype)

    def zero_gstats(self):
        return {
            name: jnp.zeros((self.n_stages,) + h.g_factor_shape, jnp.float32)
            for name, h in self.stage_registry.layers.items()
        }

    def apply(self, params, tokens, gstats=None):
        """Pipelined forward: tokens (B, S) -> logits (B, S, V).

        Returns (logits, a_stats, counts); ``a_stats`` have a leading
        stage axis sharded over ``pipe``.
        """
        if gstats is None:
            gstats = self.zero_gstats()
        b, s = tokens.shape
        m = self.n_microbatches
        self._validate_batch(b)
        x = self._embed(params, tokens)
        x_feed = x.reshape(m, b // m, s, self.d_model)

        gspec = {k: P(PIPE_AXIS) for k in gstats}
        # (M, B_m, S, D) feed/output: the per-microbatch batch dim shards
        # over the data axes; each data peer pipelines its own batch shard.
        bspec = P(None, self.data_axes) if self.data_axes else P()
        out, a_stats, counts = jax.shard_map(
            self._pipeline_body,
            mesh=self.mesh,
            in_specs=(P(PIPE_AXIS), bspec, gspec),
            out_specs=(bspec, {k: P(PIPE_AXIS) for k in gstats}, P(PIPE_AXIS)),
            axis_names=self._manual,  # model stays automatic (TP via GSPMD)
        )(params['stages'], x_feed, gstats)
        x = out.reshape(b, s, self.d_model)
        x = self.ln_f.apply({'params': params['ln_f']}, x.astype(jnp.float32))
        logits = self.head.apply({'params': params['head']}, x)
        return logits, a_stats, counts

    # ------------------------------------------------------------- 1f1b

    def _body_1f1b(
        self, stage_params, head_params, lnf_params, x_feed, t_feed, gstats
    ):
        """shard_map body: the combined F/B schedule over all ticks.

        Args (local views):
            stage_params: this stage's params (leading dim 1).
            head_params / lnf_params: head + final-norm params — replicated
                over the manual (pipe/data) axes; with tp > 1 the head
                kernel is vocab-sharded over the AUTOMATIC model axis, so
                head logic in this body must stay GSPMD-partitionable (no
                ops assuming a full local vocab copy).
            x_feed: (M, B_m, S, D) microbatch activations.
            t_feed: (M, B_m, S) target ids.
            gstats: zero g-tap dummies, leading dim 1 (this stage's slice).
        Returns (local views):
            loss_sum: () local sum of token NLLs / total_tokens.
            stage_grads: this stage's param grads (leading dim 1).
            head_grads / lnf_grads: zero except on the last stage.
            a_stats / g_stats: dict name -> (1, d, d) summed statistics.
            counts: (1,) microbatches processed by this stage's B slots.
            xbar: (M, B_m, S, D) input cotangents (real on stage 0 only).
        """
        sp = jax.tree_util.tree_map(lambda x: x[0], stage_params)
        gst = {k: v[0] for k, v in gstats.items()}
        n = self.n_stages
        m = self.n_microbatches
        registry = self.stage_registry
        all_axes = (PIPE_AXIS,) + self.data_axes
        if self.data_axes:
            vary = lambda t: jax.tree_util.tree_map(
                lambda v: jax.lax.pcast(v, self.data_axes, to='varying'), t
            )
            sp, gst = vary(sp), vary(gst)
            x_feed = jax.lax.pcast(x_feed, (PIPE_AXIS,), to='varying')
            t_feed = jax.lax.pcast(t_feed, (PIPE_AXIS,), to='varying')
        # head/ln_f arrive fully replicated (P()): vary over every axis so
        # the cond branches and accumulators agree
        head_params, lnf_params = jax.tree_util.tree_map(
            lambda v: jax.lax.pcast(v, all_axes, to='varying'),
            (head_params, lnf_params),
        )
        stage_idx = jax.lax.axis_index(PIPE_AXIS)
        if self.data_axes:
            stage_idx = jax.lax.pcast(stage_idx, self.data_axes, to='varying')
        b_m, s_len, d = x_feed.shape[1:]
        ticks = m + 2 * n - 2
        ring = 2 * n - 1
        dp = 1
        for ax in self.data_axes:
            dp *= int(self.mesh.shape[ax])
        total_tokens = float(m * b_m * s_len * dp)
        fwd_perm = [(j, (j + 1) % n) for j in range(n)]
        bwd_perm = [(j, (j - 1) % n) for j in range(n)]

        head_loss = self._make_head_loss(total_tokens)
        zero_a = {
            name: jnp.zeros(h.a_factor_shape, jnp.float32)
            for name, h in registry.layers.items()
        }
        zeros_like_vary = self._zeros_like_vary(all_axes)

        carry0 = dict(
            x_f=zeros_like_vary(jnp.zeros((b_m, s_len, d), self.dtype)),
            g_b=zeros_like_vary(jnp.zeros((b_m, s_len, d), self.dtype)),
            resid=zeros_like_vary(jnp.zeros((ring, b_m, s_len, d), self.dtype)),
            xbar=zeros_like_vary(jnp.zeros((m, b_m, s_len, d), self.dtype)),
            loss=zeros_like_vary(jnp.zeros((), jnp.float32)),
            sgrads=zeros_like_vary(
                jax.tree_util.tree_map(jnp.zeros_like, sp)
            ),
            hgrads=zeros_like_vary(
                jax.tree_util.tree_map(
                    lambda v: jnp.zeros_like(v, jnp.float32), head_params
                )
            ),
            lgrads=zeros_like_vary(
                jax.tree_util.tree_map(
                    lambda v: jnp.zeros_like(v, jnp.float32), lnf_params
                )
            ),
            a_acc=zeros_like_vary(zero_a),
            g_acc=zeros_like_vary(
                {k: jnp.zeros_like(v) for k, v in gst.items()}
            ),
            n_b=zeros_like_vary(jnp.zeros((), jnp.float32)),
        )

        def slot_b_feed(m_b):
            return jnp.clip(m_b, 0, m - 1)

        def tick(carry, t):
            # ---------------- forward slot: microbatch t - stage ----------
            m_f = t - stage_idx
            f_valid = jnp.logical_and(m_f >= 0, m_f < m)
            f_validf = f_valid.astype(jnp.float32)
            feed = jax.lax.dynamic_index_in_dim(
                x_feed, jnp.clip(m_f, 0, m - 1), keepdims=False
            )
            x_in = jnp.where(stage_idx == 0, feed, carry['x_f'])
            x_in = x_in * f_validf.astype(x_in.dtype)
            y = self.stage.apply({'params': sp}, x_in)
            y = y * f_validf.astype(y.dtype)
            # store the stage input for the backward recompute
            slot_f = jnp.clip(m_f, 0, m - 1) % ring
            resid = jax.lax.dynamic_update_index_in_dim(
                carry['resid'],
                jnp.where(f_valid, x_in, jax.lax.dynamic_index_in_dim(
                    carry['resid'], slot_f, keepdims=False)),
                slot_f, 0,
            )

            # last stage: head + loss + cotangent for this microbatch, the
            # same tick its forward completes (the 1F1B pivot). Other
            # stages skip the head entirely — they are off the tick's
            # critical path while the last stage computes it.
            tgt = jax.lax.dynamic_index_in_dim(
                t_feed, jnp.clip(m_f, 0, m - 1), keepdims=False
            )

            def do_head(_):
                lval, pull = jax.vjp(head_loss, y, head_params, lnf_params, tgt)
                ybar, hbar, lbar, _ = pull(f_validf)
                return lval * f_validf, ybar, hbar, lbar

            def no_head(_):
                # fresh zeros are unvarying; pcast so both branches agree
                return jax.tree_util.tree_map(
                    lambda v: jax.lax.pcast(
                        jnp.zeros(v.shape, v.dtype), all_axes, to='varying'
                    ),
                    (
                        jnp.zeros((), jnp.float32),
                        jnp.zeros_like(y),
                        jax.tree_util.tree_map(
                            lambda v: jnp.zeros_like(v, jnp.float32),
                            head_params,
                        ),
                        jax.tree_util.tree_map(
                            lambda v: jnp.zeros_like(v, jnp.float32),
                            lnf_params,
                        ),
                    ),
                )

            lval, ybar_local, hbar, lbar = jax.lax.cond(
                stage_idx == n - 1, do_head, no_head, None
            )

            # ---------------- backward slot: microbatch t - (2S-2-stage) --
            m_b = t - (2 * n - 2 - stage_idx)
            b_valid = jnp.logical_and(m_b >= 0, m_b < m)
            b_validf = b_valid.astype(jnp.float32)
            slot_b = jnp.clip(m_b, 0, m - 1) % ring
            x_saved = jax.lax.dynamic_index_in_dim(resid, slot_b, keepdims=False)
            # cotangent: in-tick on the last stage (m_b == m_f there), the
            # ppermuted one from stage s+1 elsewhere
            ybar = jnp.where(stage_idx == n - 1, ybar_local, carry['g_b'])
            ybar = ybar * b_validf.astype(ybar.dtype)
            y_re, pull, tick_a = jax.vjp(
                lambda sp_, x_, gd_: self._stage_apply_captured(
                    sp_, gd_, x_, b_validf
                ),
                sp, x_saved, gst, has_aux=True,
            )
            del y_re
            spbar, xbar_mb, gdbar = pull(ybar)

            carry = dict(
                x_f=jax.lax.ppermute(y, PIPE_AXIS, fwd_perm),
                g_b=jax.lax.ppermute(
                    xbar_mb.astype(self.dtype), PIPE_AXIS, bwd_perm
                ),
                resid=resid,
                xbar=jax.lax.dynamic_update_index_in_dim(
                    carry['xbar'],
                    jnp.where(
                        jnp.logical_and(stage_idx == 0, b_valid),
                        xbar_mb.astype(self.dtype),
                        jax.lax.dynamic_index_in_dim(
                            carry['xbar'], slot_b_feed(m_b), keepdims=False
                        ),
                    ),
                    slot_b_feed(m_b), 0,
                ),
                loss=carry['loss'] + lval,
                sgrads=jax.tree_util.tree_map(
                    lambda acc, new: acc + new, carry['sgrads'], spbar
                ),
                hgrads=jax.tree_util.tree_map(
                    lambda acc, new: acc + new, carry['hgrads'], hbar
                ),
                lgrads=jax.tree_util.tree_map(
                    lambda acc, new: acc + new, carry['lgrads'], lbar
                ),
                a_acc={k: carry['a_acc'][k] + tick_a[k] for k in tick_a},
                g_acc={k: carry['g_acc'][k] + gdbar[k] for k in gdbar},
                n_b=carry['n_b'] + b_validf,
            )
            return carry, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(ticks))

        loss_sum = jax.lax.psum(carry['loss'], all_axes)
        sgrads = carry['sgrads']
        hgrads = jax.tree_util.tree_map(
            lambda v: jax.lax.psum(v, all_axes), carry['hgrads']
        )
        lgrads = jax.tree_util.tree_map(
            lambda v: jax.lax.psum(v, all_axes), carry['lgrads']
        )
        a_acc, g_acc, n_b = carry['a_acc'], carry['g_acc'], carry['n_b']
        if self.data_axes:
            # DP reductions: stage grads and factor stats sum over the data
            # peers (the reference's factor allreduce over the DP group)
            sgrads = jax.tree_util.tree_map(
                lambda v: jax.lax.psum(v, self.data_axes), sgrads
            )
            a_acc = {
                k: jax.lax.psum(v, self.data_axes) for k, v in a_acc.items()
            }
            g_acc = {
                k: jax.lax.psum(v, self.data_axes) for k, v in g_acc.items()
            }
            n_b = jax.lax.psum(n_b, self.data_axes)
        ex = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        # xbar holds real cotangents on stage 0 and zeros elsewhere: the
        # psum over pipe is the broadcast of stage 0's buffer to the world
        xbar = jax.lax.psum(carry['xbar'], PIPE_AXIS)
        return (
            loss_sum,
            ex(sgrads),
            hgrads,
            lgrads,
            ex(a_acc),
            ex(g_acc),
            n_b[None],
            xbar,
        )

    def _loss_and_stats_1f1b(self, params, batch):
        """1F1B: loss, grads, and capture stats from ONE combined scan."""
        tokens, targets = batch
        b, s = tokens.shape
        m = self.n_microbatches
        self._validate_batch(b)
        gstats0 = self.zero_gstats()

        def embed_fn(ep):
            x = self._embed({'embed': ep['embed'],
                             'pos_embed': ep['pos_embed']}, tokens)
            return x.reshape(m, b // m, s, self.d_model)

        epar = {'embed': params['embed'], 'pos_embed': params['pos_embed']}
        x_feed, embed_pull = jax.vjp(embed_fn, epar)
        t_feed = targets.reshape(m, b // m, s)

        gspec = {k: P(PIPE_AXIS) for k in gstats0}
        bspec = P(None, self.data_axes) if self.data_axes else P()
        tspec = bspec
        out = jax.shard_map(
            self._body_1f1b,
            mesh=self.mesh,
            axis_names=self._manual,  # model stays automatic (TP via GSPMD)
            in_specs=(P(PIPE_AXIS), P(), P(), bspec, tspec, gspec),
            out_specs=(
                P(),                # loss (psum'd)
                jax.tree_util.tree_map(lambda _: P(PIPE_AXIS),
                                       params['stages']),
                P(),                # head grads (psum'd)
                P(),                # ln_f grads (psum'd)
                {k: P(PIPE_AXIS) for k in gstats0},
                {k: P(PIPE_AXIS) for k in gstats0},
                P(PIPE_AXIS),       # counts
                bspec,              # xbar feed
            ),
        )(params['stages'], params['head'], params['ln_f'], x_feed, t_feed,
          gstats0)
        loss, sgrads, hgrads, lgrads, a_stats, g_stats, counts, xbar = out
        (egrads,) = embed_pull(xbar)
        grads = {
            'embed': egrads['embed'],
            'pos_embed': egrads['pos_embed'],
            'stages': sgrads,
            'head': hgrads,
            'ln_f': lgrads,
        }
        denom = jnp.maximum(counts, 1.0)
        a_avg = {k: v / denom[:, None, None] for k, v in a_stats.items()}
        # g-tap cotangents carry the 1/total_tokens loss normalization; the
        # per-count division matches the gpipe path's convention
        g_avg = {k: v / denom[:, None, None] for k, v in g_stats.items()}
        return loss, grads, capture_lib.CapturedStats(a=a_avg, g=g_avg)

    # ------------------------------------------------------------- loss

    def loss_and_stats(self, params, batch):
        """(loss, grads, stage-stacked stats) in one backward pass."""
        if self.schedule == '1f1b':
            return self._loss_and_stats_1f1b(params, batch)

        def tapped(params, gstats):
            tokens, targets = batch
            logits, a_stats, counts = self.apply(params, tokens, gstats)
            nll = losses_lib.vocab_parallel_nll(logits, targets)
            return jnp.mean(nll), (a_stats, counts)

        gstats0 = self.zero_gstats()
        (loss, (a_stats, counts)), (grads, g_stats) = jax.value_and_grad(
            tapped, argnums=(0, 1), has_aux=True
        )(params, gstats0)
        denom = jnp.maximum(counts, 1.0)  # (n_stages,)
        a_avg = {
            k: v / denom[:, None, None] for k, v in a_stats.items()
        }
        g_avg = {
            k: v / denom[:, None, None] for k, v in g_stats.items()
        }
        return loss, grads, capture_lib.CapturedStats(a=a_avg, g=g_avg)


@dataclasses.dataclass
class PipelineKFAC:
    """K-FAC for a :class:`PipelinedLM`'s stage layers.

    State arrays keep the leading stage axis sharded over ``pipe``: factor
    updates, decompositions, and preconditioning all run inside one
    shard_map with zero cross-stage traffic (the reference's
    MEM-OPT-among-pipe-peers, kfac/gpt_neox/assignment.py:116-130). The
    kl-clip sum is the only cross-stage collective (one psum).

    Both compute methods are supported: EIGEN (eigendecompositions in the
    ``qa/qg/da/dg`` slots) and INVERSE (damped inverses in ``qa/qg``,
    solver per ``config.inverse_solver`` — ``'newton_schulz'`` keeps
    pipelined K-FAC entirely matmul-based on TPU).
    """

    config: KFACPreconditioner
    model: PipelinedLM

    def __post_init__(self) -> None:
        from kfac_tpu import enums

        self.mesh = self.model.mesh
        self.registry = self.model.stage_registry
        self.n_stages = self.model.n_stages
        # DP axes of a pipeline_mesh: each stage's eigendecompositions
        # round-robin over these peers instead of being recomputed by every
        # data replica (eigh work / dp wall-clock), then psum-share. The
        # model axis stays automatic (factors/decomps are global over TP),
        # mirroring PipelinedLM's manual set.
        self._dp_axes = tuple(
            ax
            for ax in self.mesh.axis_names
            if ax not in (PIPE_AXIS, mesh_lib.MODEL_AXIS)
            and int(self.mesh.shape[ax]) > 1
        )
        self._manual = frozenset(
            ax
            for ax in self.mesh.axis_names
            if ax != mesh_lib.MODEL_AXIS
        )
        self._dp_size = 1
        for ax in self._dp_axes:
            self._dp_size *= int(self.mesh.shape[ax])
        self._eigen = self.config.compute_method == enums.ComputeMethod.EIGEN
        if self.config.prediv_eigenvalues:
            raise NotImplementedError(
                'prediv_eigenvalues is not supported by PipelineKFAC'
            )

    def _peer_index(self):
        """Linear index of this device within the DP axes (inside shard_map)."""
        idx = jnp.asarray(0, jnp.int32)
        for ax in self._dp_axes:
            idx = idx * int(self.mesh.shape[ax]) + jax.lax.axis_index(ax)
        return idx

    def _make_decomp(self, damping, a_mat, g_mat, like, li):
        """Decomposition of one stage-local layer (inside shard_map).

        Returns ``compute(operand) -> (qa, qg, da, dg)``: eigendecomposition
        (EIGEN) or damped inverses in the qa/qg slots (INVERSE — the
        Newton-Schulz solver keeps this matmul-only on TPU). With DP peers
        present the work round-robins over them by layer index ``li`` and
        psum-shares, dividing decomposition wall-clock by the DP world.
        ``like`` supplies zero templates for the non-owner branch.
        """
        cfg = self.config

        def run_eigh(_):
            adec = factors_lib.compute_eigh(a_mat, cfg.inv_dtype, cfg.eigh_impl)
            gdec = factors_lib.compute_eigh(g_mat, cfg.inv_dtype, cfg.eigh_impl)
            return adec.q, gdec.q, adec.d, gdec.d

        def run_inverse(_):
            # like[0]/like[1] are the resident inverses on the INVERSE
            # path (the qa/qg slots double as a_inv/g_inv): warm-start
            # Newton-Schulz from them (safeguarded; zeros cold-start)
            inv = lambda f, prev: factors_lib.damped_inverse(
                f, damping, cfg.inv_dtype, cfg.inverse_solver,
                cfg.newton_schulz_iters, x0=prev,
            )
            return (
                inv(a_mat, like[0]), inv(g_mat, like[1]),
                jnp.zeros_like(like[2]), jnp.zeros_like(like[3]),
            )

        run_decomp = run_eigh if self._eigen else run_inverse
        if not self._dp_axes:
            return run_decomp
        owner = li % self._dp_size

        def vary(t):
            return jax.lax.pcast(t, self._dp_axes, to='varying')

        def dp_compute(_):
            out = jax.lax.cond(
                self._peer_index() == owner,
                lambda _: tuple(map(vary, run_decomp(None))),
                lambda _: tuple(
                    vary(jnp.zeros_like(t)) for t in like
                ),
                None,
            )
            return tuple(jax.lax.psum(t, self._dp_axes) for t in out)

        return dp_compute

    def rematerialize(self, state):
        """Recompute all decompositions from the stored factors (used by
        checkpoint restore: only step + factors are durable)."""
        cfg = self.config
        damping = _resolve(cfg.damping, state['step'])
        names = list(self.registry.layers)

        def body(a, g, qa, qg, da, dg):
            sq = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
            a, g, qa, qg, da, dg = map(sq, (a, g, qa, qg, da, dg))
            new_qa, new_qg, new_da, new_dg = {}, {}, {}, {}
            for li, name in enumerate(names):
                compute = self._make_decomp(
                    damping, a[name], g[name],
                    (qa[name], qg[name], da[name], dg[name]), li,
                )
                (
                    new_qa[name], new_qg[name],
                    new_da[name], new_dg[name],
                ) = compute(None)
            ex = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            return ex(new_qa), ex(new_qg), ex(new_da), ex(new_dg)

        specs = tuple({k: P(PIPE_AXIS) for k in names} for _ in range(6))
        new_qa, new_qg, new_da, new_dg = jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=specs,
            out_specs=specs[:4],
            axis_names=self._manual,
        )(
            state['a'], state['g'], state['qa'], state['qg'],
            state['da'], state['dg'],
        )
        return {
            **state,
            'qa': new_qa, 'qg': new_qg, 'da': new_da, 'dg': new_dg,
        }

    def describe(self) -> str:
        """Registration + placement dump (reference parity:
        kfac/preconditioner.py:264-268,300): stage topology and the
        stage-local MEM-OPT placement."""
        lines = [
            f'PipelineKFAC: {len(self.registry.layers)} layers per stage '
            f'x {self.n_stages} stages (mesh {dict(self.mesh.shape)}), '
            'placement=MEM-OPT among pipe peers (stage-local state), '
            f'decomposition round-robin over dp={self._dp_size}, '
            f'method={self.config.compute_method.name}',
            self.config.describe(),
        ]
        return '\n'.join(lines)

    def extract_factors(self, state) -> dict[str, dict[str, jax.Array]]:
        """Per-layer factors with their stage axis (portable across
        pipeline engine configs with the SAME n_stages; cross-stage-count
        migration would need a stage re-partition, which the reference
        does not support either)."""
        return {
            name: {'a': state['a'][name], 'g': state['g'][name]}
            for name in state['a']
        }

    def insert_factors(self, state, factors):
        """Inverse of :meth:`extract_factors`; call
        :meth:`rematerialize` afterwards."""
        new = {
            **state,
            'a': dict(state['a']),
            'g': dict(state['g']),
        }
        spec = self._spec()
        for name, fg in factors.items():
            if name in new['a']:
                new['a'][name] = jax.device_put(
                    fg['a'].astype(self.config.factor_dtype), spec
                )
                new['g'][name] = jax.device_put(
                    fg['g'].astype(self.config.factor_dtype), spec
                )
        return new

    def _spec(self):
        return NamedSharding(self.mesh, P(PIPE_AXIS))

    def init(self):
        def build():
            a, g, qa, qg, da, dg = {}, {}, {}, {}, {}, {}
            ns = self.n_stages
            cfg = self.config
            for name, h in self.registry.layers.items():
                na, ng = h.a_factor_shape[0], h.g_factor_shape[0]
                a[name] = jnp.broadcast_to(
                    jnp.eye(na, dtype=cfg.factor_dtype), (ns, na, na)
                )
                g[name] = jnp.broadcast_to(
                    jnp.eye(ng, dtype=cfg.factor_dtype), (ns, ng, ng)
                )
                qa[name] = jnp.zeros((ns, na, na), cfg.inv_dtype)
                qg[name] = jnp.zeros((ns, ng, ng), cfg.inv_dtype)
                da[name] = jnp.zeros((ns, na), cfg.inv_dtype)
                dg[name] = jnp.zeros((ns, ng), cfg.inv_dtype)
            return {
                'step': jnp.asarray(0, jnp.int32),
                'a': a, 'g': g, 'qa': qa, 'qg': qg, 'da': da, 'dg': dg,
            }

        state = build()
        spec = self._spec()
        for key in ('a', 'g', 'qa', 'qg', 'da', 'dg'):
            state[key] = {
                k: jax.device_put(v, spec) for k, v in state[key].items()
            }
        # `step` must live on the full pipe mesh (replicated), not a single
        # device: leaving it unplaced commits it to device 0 and any jit over
        # (params-on-mesh, state) fails with incompatible-devices. Restore
        # inherits this placement because orbax restores each leaf onto the
        # template sharding, and checkpoint.restore templates from init().
        state['step'] = jax.device_put(
            state['step'], NamedSharding(self.mesh, P())
        )
        return state

    def step(self, state, grads, stats):
        """Update factors/decomps and precondition stage grads (in place of
        the stage slice of ``grads``)."""
        cfg = self.config
        step = state['step']
        damping = _resolve(cfg.damping, step)
        alpha = _resolve(cfg.factor_decay, step)
        lr = _resolve(cfg.lr, step)
        names = list(self.registry.layers)
        helpers = self.registry.layers

        do_factors = step % _resolve(cfg.factor_update_steps, step) == 0
        do_inverses = step % _resolve(cfg.inv_update_steps, step) == 0

        def body(a, g, qa, qg, da, dg, sa, sg, stage_grads):
            # stage-local views: leading dim = stages per rank (1 for the
            # plain pipeline, virtual_chunks for the interleaved one —
            # a static Python loop over local chunks keeps the per-stage
            # math identical; the kl-clip sum spans all chunks of all
            # ranks before any scaling)
            local = next(iter(a.values())).shape[0]
            per_ci: list[tuple] = []
            vg = jnp.zeros((), jnp.float32)
            for ci in range(local):
                sq = lambda t: jax.tree_util.tree_map(lambda x: x[ci], t)
                a_c, g_c, qa_c, qg_c, da_c, dg_c, sa_c, sg_c = map(
                    sq, (a, g, qa, qg, da, dg, sa, sg)
                )
                sgrads = sq(stage_grads)
                new_a, new_g = {}, {}
                new_qa, new_qg, new_da, new_dg = {}, {}, {}, {}
                pre = {}
                for li, name in enumerate(names):
                    h = helpers[name]
                    na_ = jax.lax.cond(
                        do_factors,
                        lambda _: factors_lib.ema_update(
                            a_c[name], sa_c[name].astype(cfg.factor_dtype),
                            alpha,
                        ),
                        lambda _: a_c[name],
                        None,
                    )
                    ng_ = jax.lax.cond(
                        do_factors,
                        lambda _: factors_lib.ema_update(
                            g_c[name], sg_c[name].astype(cfg.factor_dtype),
                            alpha,
                        ),
                        lambda _: g_c[name],
                        None,
                    )
                    new_a[name], new_g[name] = na_, ng_

                    # round-robin owner over DP peers: offset by chunk so
                    # multi-chunk ranks spread decompositions too
                    compute = self._make_decomp(
                        damping, na_, ng_,
                        (qa_c[name], qg_c[name], da_c[name], dg_c[name]),
                        ci * len(names) + li,
                    )
                    qa_, qg_, da_, dg_ = jax.lax.cond(
                        do_inverses,
                        compute,
                        lambda _: (
                            qa_c[name], qg_c[name], da_c[name], dg_c[name]
                        ),
                        None,
                    )
                    new_qa[name], new_qg[name] = qa_, qg_
                    new_da[name], new_dg[name] = da_, dg_

                    path = self.registry.param_paths[name]
                    node = sgrads
                    for k in path:
                        node = node[k]
                    gmat = h.grads_to_matrix(dict(node))
                    if self._eigen:
                        pmat = factors_lib.eigen_preconditioned_grad(
                            gmat,
                            factors_lib.EigenDecomp(qa_, da_),
                            factors_lib.EigenDecomp(qg_, dg_),
                            damping,
                        )
                    else:
                        pmat = factors_lib.inverse_preconditioned_grad(
                            gmat, qa_, qg_
                        )
                    if cfg.kl_clip is not None:
                        vg = vg + factors_lib.kl_clip_terms(
                            pmat, gmat, lr
                        )
                    pre[name] = pmat
                per_ci.append(
                    (new_a, new_g, new_qa, new_qg, new_da, new_dg,
                     sgrads, pre)
                )

            if cfg.kl_clip is not None:
                vg = jax.lax.psum(vg, PIPE_AXIS)
                scale = factors_lib.kl_clip_scale(
                    vg, _resolve(cfg.kl_clip, step)
                )
            else:
                scale = 1.0

            out_per_ci = []
            for new_a, new_g, new_qa, new_qg, new_da, new_dg, sgrads, pre \
                    in per_ci:
                out_grads = sgrads
                for name in names:
                    h = helpers[name]
                    new_leaves = h.matrix_to_grads(
                        factors_lib.kl_clip_apply(pre[name], scale)
                    )
                    out_grads = registry_lib.merge_layer_grads(
                        out_grads, {name: new_leaves},
                        registry_lib.Registry(
                            layers={name: h},
                            param_paths={
                                name: self.registry.param_paths[name]
                            },
                        ),
                    )
                out_per_ci.append(
                    (new_a, new_g, new_qa, new_qg, new_da, new_dg,
                     out_grads)
                )
            stack = lambda *ts: jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *ts
            )
            return tuple(
                stack(*(out_per_ci[ci][j] for ci in range(local)))
                for j in range(7)
            )

        # 8 stage-sharded dict specs: a, g, qa, qg, da, dg, stats.a, stats.g
        state_specs = tuple({k: P(PIPE_AXIS) for k in names} for _ in range(8))
        grads_spec = jax.tree_util.tree_map(
            lambda _: P(PIPE_AXIS), grads['stages']
        )
        new_a, new_g, new_qa, new_qg, new_da, new_dg, new_stage_grads = (
            jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=state_specs + (grads_spec,),
                out_specs=state_specs[:6] + (grads_spec,),
                axis_names=self._manual,
            )(
                state['a'], state['g'], state['qa'], state['qg'],
                state['da'], state['dg'], stats.a, stats.g, grads['stages'],
            )
        )
        new_state = {
            'step': step + 1,
            'a': new_a, 'g': new_g, 'qa': new_qa, 'qg': new_qg,
            'da': new_da, 'dg': new_dg,
        }
        new_grads = dict(grads)
        new_grads['stages'] = new_stage_grads
        return new_state, new_grads
