"""Single-slot interleaved 1F1B pipeline scan (Megatron virtual stages).

:class:`InterleavedPipelinedLM` assigns each pipeline rank ``v`` model
chunks (logical stage ``s = c*p + r`` lives on rank ``r`` as its chunk
``c``) and drives them with the SINGLE-SLOT schedule tables from
:func:`kfac_tpu.parallel.interleaved.generate_single_slot`: one F *or* B
chunk execution per rank per tick, so fill/drain are paid in chunk units
and the per-rank bubble drops to ``2*(p-1)/v`` stage-units — the full
Megatron reduction (Narayanan et al. 2021, §2.2), which the 2-slot
combined scan of :class:`kfac_tpu.parallel.pipeline.PipelinedLM`
(schedule='1f1b') structurally caps at ~25%.

The reference rides DeepSpeed's PipelineEngine and has no interleaving;
this is the beyond-reference pipeline milestone (docs/ROADMAP.md gap #3).

Execution model (one ``lax.scan`` over ticks inside one ``shard_map``):

- Stage parameters stack RANK-MAJOR: stack index ``r*v + c`` holds
  logical stage ``c*p + r``, so ``P(pipe)`` on the leading axis gives
  each rank exactly its ``v`` chunks. :func:`logical_to_stack` converts.
- Each tick looks up this rank's ``(kind, chunk, mb, slot)`` in the
  static tables (a closed-over constant indexed by ``axis_index``) and
  ``lax.switch``es between an idle, a forward (plain chunk apply), and a
  backward body (chunk recompute under ``jax.vjp`` with the capture
  interceptor + g-taps — identical semantics to the 2-slot scan). The
  LAST logical stage's backward recomputes head+loss+cotangent in-op
  from the saved stage input, so it needs no external cotangent.
- Activations and cotangents ``ppermute`` between ticks UNCONDITIONALLY
  (collectives must run uniformly across ranks; idle/other-kind ticks
  send zeros flagged invalid) into small per-chunk inboxes whose depths
  the schedule generator proved sufficient (messages per (rank, chunk)
  are produced and consumed in microbatch order, so ``mb % depth``
  never collides).
- Stage inputs persist in a residual ring whose slots the generator
  allocated per-op (``slot`` column) — no runtime free-list, and the
  ring size is exactly the schedule's true in-flight maximum.

Memory: the ring holds ``2*(p-1) + (v-1)*p + 1`` stage inputs (the
interleaved warmup depth) vs the 2-slot scan's ``2*p - 1`` — deeper
in-flight is the price of the smaller bubble, exactly as in Megatron.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kfac_tpu.layers import capture as capture_lib
from kfac_tpu.parallel import interleaved as interleaved_lib
from kfac_tpu.parallel import pipeline as pipeline_lib
from kfac_tpu.parallel.pipeline import PIPE_AXIS


def logical_to_stack(p: int, v: int, s: int) -> int:
    """Stack index (rank-major ``r*v + c``) of logical stage ``s = c*p + r``."""
    return (s % p) * v + s // p


@dataclasses.dataclass
class InterleavedPipelinedLM(pipeline_lib.PipelinedLM):
    """Decoder LM pipelined with ``virtual_chunks`` model chunks per rank
    under the single-slot interleaved 1F1B schedule.

    Same surface as :class:`PipelinedLM` (init / loss_and_stats /
    PipelineKFAC integration); ``n_stages`` becomes the TOTAL logical
    stage count ``p * virtual_chunks`` and ``n_microbatches`` must be a
    positive multiple of the rank count ``p`` (Megatron's constraint).
    """

    virtual_chunks: int = 2

    def _executes_interleaved(self) -> bool:
        return True

    def _chunks_per_rank(self) -> int:
        # consulted by PipelinedLM.__post_init__ BEFORE it builds the
        # stage module/registry, so construction happens exactly once
        # with n_stages = p * virtual_chunks
        if self.virtual_chunks < 1:
            raise ValueError(
                f'virtual_chunks must be >= 1, got {self.virtual_chunks}'
            )
        return self.virtual_chunks

    def __post_init__(self) -> None:
        super().__post_init__()
        self.p_ranks = int(self.mesh.shape[PIPE_AXIS])
        self.schedule = 'interleaved'
        self._sched = interleaved_lib.generate_single_slot(
            self.p_ranks, self.virtual_chunks, self.n_microbatches
        )

    def apply(self, params, tokens, gstats=None):
        raise NotImplementedError(
            'the forward-only apply() path runs the plain per-rank '
            'pipeline and does not understand virtual chunks; use '
            'loss_and_stats (the single-slot scan) or a PipelinedLM'
        )

    # ------------------------------------------------------------- body

    def _body_interleaved(
        self, stage_params, head_params, lnf_params, x_feed, t_feed, gstats
    ):
        """shard_map body: the single-slot schedule over all ticks.

        Local views: ``stage_params`` / ``gstats`` carry this rank's ``v``
        chunks on their leading axis; ``x_feed``/``t_feed`` are the
        microbatch feeds; outputs mirror
        :meth:`PipelinedLM._body_1f1b` with per-chunk leading axes.
        """
        sp = stage_params
        gst = gstats
        p = self.p_ranks
        v = self.virtual_chunks
        m = self.n_microbatches
        sched = self._sched
        ring, d_act, d_cot = sched.ring, sched.act_depth, sched.cot_depth
        registry = self.stage_registry
        all_axes = (PIPE_AXIS,) + self.data_axes
        if self.data_axes:
            vary = lambda t: jax.tree_util.tree_map(
                lambda x: jax.lax.pcast(x, self.data_axes, to='varying'), t
            )
            sp, gst = vary(sp), vary(gst)
            x_feed = jax.lax.pcast(x_feed, (PIPE_AXIS,), to='varying')
            t_feed = jax.lax.pcast(t_feed, (PIPE_AXIS,), to='varying')
        head_params, lnf_params = jax.tree_util.tree_map(
            lambda x: jax.lax.pcast(x, all_axes, to='varying'),
            (head_params, lnf_params),
        )
        rank = jax.lax.axis_index(PIPE_AXIS)
        if self.data_axes:
            rank = jax.lax.pcast(rank, self.data_axes, to='varying')
        b_m, s_len, d = x_feed.shape[1:]
        last_stage = p * v - 1
        dp = 1
        for ax in self.data_axes:
            dp *= int(self.mesh.shape[ax])
        total_tokens = float(m * b_m * s_len * dp)
        fwd_perm = [(j, (j + 1) % p) for j in range(p)]
        bwd_perm = [(j, (j - 1) % p) for j in range(p)]
        # this rank's tick table: (ticks, 4) — static array, varying index
        ops_r = jnp.take(jnp.asarray(sched.ops), rank, axis=1)

        head_loss = self._make_head_loss(total_tokens)
        zeros_like_vary = self._zeros_like_vary(all_axes)
        zero_a = {
            name: jnp.zeros((v,) + h.a_factor_shape, jnp.float32)
            for name, h in registry.layers.items()
        }
        carry0 = dict(
            act_in=zeros_like_vary(
                jnp.zeros((v, d_act, b_m, s_len, d), self.dtype)
            ),
            cot_in=zeros_like_vary(
                jnp.zeros((v, d_cot, b_m, s_len, d), self.dtype)
            ),
            resid=zeros_like_vary(
                jnp.zeros((ring, b_m, s_len, d), self.dtype)
            ),
            xbar=zeros_like_vary(jnp.zeros((m, b_m, s_len, d), self.dtype)),
            loss=zeros_like_vary(jnp.zeros((), jnp.float32)),
            sgrads=zeros_like_vary(
                jax.tree_util.tree_map(jnp.zeros_like, sp)
            ),
            hgrads=zeros_like_vary(
                jax.tree_util.tree_map(
                    lambda x: jnp.zeros_like(x, jnp.float32), head_params
                )
            ),
            lgrads=zeros_like_vary(
                jax.tree_util.tree_map(
                    lambda x: jnp.zeros_like(x, jnp.float32), lnf_params
                )
            ),
            a_acc=zeros_like_vary(zero_a),
            g_acc=zeros_like_vary(
                {k: jnp.zeros_like(x) for k, x in gst.items()}
            ),
            n_b=zeros_like_vary(jnp.zeros((v,), jnp.float32)),
            # per-rank (executed F, executed B, idle) tick counters —
            # incremented from the live op kind each tick, so the counts
            # come out of the executed scan, not the static tables
            ticks=zeros_like_vary(jnp.zeros((3,), jnp.int32)),
        )
        zero_msg = zeros_like_vary(jnp.zeros((b_m, s_len, d), self.dtype))
        zero_meta = zeros_like_vary(jnp.zeros((3,), jnp.int32))

        def tick(carry, op):
            kind, chunk, mb, slot = op[0], op[1], op[2], op[3]
            chunk_c = jnp.clip(chunk, 0, v - 1)
            mb_c = jnp.clip(mb, 0, m - 1)
            slot_c = jnp.clip(slot, 0, ring - 1)
            stage_s = chunk_c * p + rank  # logical stage of this op
            sp_c = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, chunk_c, keepdims=False
                ),
                sp,
            )
            gst_c = {
                k: jax.lax.dynamic_index_in_dim(gv, chunk_c, keepdims=False)
                for k, gv in gst.items()
            }

            def idle_branch(carry):
                return carry, zero_msg, zero_meta, zero_msg, zero_meta

            def f_branch(carry):
                feed = jax.lax.dynamic_index_in_dim(
                    x_feed, mb_c, keepdims=False
                )
                inbox = jax.lax.dynamic_index_in_dim(
                    jax.lax.dynamic_index_in_dim(
                        carry['act_in'], chunk_c, keepdims=False
                    ),
                    mb_c % d_act, keepdims=False,
                )
                x_in = jnp.where(stage_s == 0, feed, inbox)
                # the last logical stage's output is consumed by ITS OWN
                # backward (head+loss recompute under vjp), never sent —
                # skip the forward entirely there instead of computing a
                # discarded y
                y = jax.lax.cond(
                    stage_s < last_stage,
                    lambda x: self.stage.apply({'params': sp_c}, x).astype(
                        self.dtype
                    ),
                    # fresh zeros are vma-unvarying; match the true branch
                    lambda x: jax.lax.pcast(
                        jnp.zeros(x.shape, self.dtype), all_axes,
                        to='varying',
                    ),
                    x_in,
                )
                new = dict(carry)
                new['resid'] = jax.lax.dynamic_update_index_in_dim(
                    carry['resid'], x_in, slot_c, 0
                )
                send_valid = (stage_s < last_stage).astype(jnp.int32)
                nxt = stage_s + 1
                meta = jnp.stack(
                    [nxt // p, mb_c, send_valid]
                ).astype(jnp.int32)
                return (
                    new, y.astype(self.dtype) * send_valid.astype(y.dtype),
                    meta, zero_msg, zero_meta,
                )

            def b_branch(carry):
                x_saved = jax.lax.dynamic_index_in_dim(
                    carry['resid'], slot_c, keepdims=False
                )
                ybar_ext = jax.lax.dynamic_index_in_dim(
                    jax.lax.dynamic_index_in_dim(
                        carry['cot_in'], chunk_c, keepdims=False
                    ),
                    mb_c % d_cot, keepdims=False,
                )
                is_last = stage_s == last_stage
                tgt = jax.lax.dynamic_index_in_dim(
                    t_feed, mb_c, keepdims=False
                )

                def primal(sp_, x_, gst_, hp, lp):
                    y, tick_a = self._stage_apply_captured(
                        sp_, gst_, x_, jnp.float32(1.0)
                    )
                    lval = jax.lax.cond(
                        is_last,
                        lambda: head_loss(y, hp, lp, tgt),
                        lambda: jax.lax.pcast(
                            jnp.zeros((), jnp.float32), all_axes,
                            to='varying',
                        ),
                    )
                    return (y, lval), tick_a

                (_, lval), pull, tick_a = jax.vjp(
                    primal, sp_c, x_saved, gst_c, head_params, lnf_params,
                    has_aux=True,
                )
                ybar = jnp.where(
                    is_last, jnp.zeros_like(ybar_ext), ybar_ext
                ).astype(self.dtype)
                spbar, xbar_x, gdbar, hbar, lbar = pull(
                    (
                        ybar,
                        jax.lax.pcast(
                            jnp.ones((), jnp.float32), all_axes,
                            to='varying',
                        ),
                    )
                )
                new = dict(carry)
                new['loss'] = carry['loss'] + lval
                new['sgrads'] = jax.tree_util.tree_map(
                    lambda acc, g: acc.at[chunk_c].add(g),
                    carry['sgrads'], spbar,
                )
                new['hgrads'] = jax.tree_util.tree_map(
                    lambda acc, g: acc + g, carry['hgrads'], hbar
                )
                new['lgrads'] = jax.tree_util.tree_map(
                    lambda acc, g: acc + g, carry['lgrads'], lbar
                )
                new['a_acc'] = {
                    k: carry['a_acc'][k].at[chunk_c].add(tick_a[k])
                    for k in tick_a
                }
                new['g_acc'] = {
                    k: carry['g_acc'][k].at[chunk_c].add(gdbar[k])
                    for k in gdbar
                }
                new['n_b'] = carry['n_b'].at[chunk_c].add(1.0)
                xbar_x = xbar_x.astype(self.dtype)
                new['xbar'] = jax.lax.dynamic_update_index_in_dim(
                    carry['xbar'],
                    jnp.where(
                        stage_s == 0,
                        xbar_x,
                        jax.lax.dynamic_index_in_dim(
                            carry['xbar'], mb_c, keepdims=False
                        ),
                    ),
                    mb_c, 0,
                )
                send_valid = (stage_s > 0).astype(jnp.int32)
                prev = jnp.maximum(stage_s - 1, 0)
                meta = jnp.stack(
                    [prev // p, mb_c, send_valid]
                ).astype(jnp.int32)
                return (
                    new, zero_msg, zero_meta,
                    xbar_x * send_valid.astype(xbar_x.dtype), meta,
                )

            carry, s_act, am, s_cot, cm = jax.lax.switch(
                kind + 1, [idle_branch, f_branch, b_branch], carry
            )
            carry['ticks'] = carry['ticks'] + jnp.stack(
                [kind == 0, kind == 1, kind < 0]
            ).astype(jnp.int32)

            # uniform collectives: every rank permutes every tick (invalid
            # messages are zeros; the metadata valid flag gates the write)
            r_act = jax.lax.ppermute(s_act, PIPE_AXIS, fwd_perm)
            r_am = jax.lax.ppermute(am, PIPE_AXIS, fwd_perm)
            r_cot = jax.lax.ppermute(s_cot, PIPE_AXIS, bwd_perm)
            r_cm = jax.lax.ppermute(cm, PIPE_AXIS, bwd_perm)

            def deliver(inbox, msg, meta, depth):
                c_i = jnp.clip(meta[0], 0, v - 1)
                s_i = jnp.clip(meta[1], 0, m - 1) % depth
                cur = jax.lax.dynamic_index_in_dim(
                    jax.lax.dynamic_index_in_dim(inbox, c_i, keepdims=False),
                    s_i, keepdims=False,
                )
                val = jnp.where(meta[2] > 0, msg, cur)
                row = jax.lax.dynamic_update_index_in_dim(
                    jax.lax.dynamic_index_in_dim(inbox, c_i, keepdims=False),
                    val, s_i, 0,
                )
                return jax.lax.dynamic_update_index_in_dim(
                    inbox, row, c_i, 0
                )

            carry['act_in'] = deliver(carry['act_in'], r_act, r_am, d_act)
            carry['cot_in'] = deliver(carry['cot_in'], r_cot, r_cm, d_cot)
            return carry, None

        carry, _ = jax.lax.scan(tick, carry0, ops_r)

        loss_sum = jax.lax.psum(carry['loss'], all_axes)
        sgrads = carry['sgrads']
        hgrads = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, all_axes), carry['hgrads']
        )
        lgrads = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, all_axes), carry['lgrads']
        )
        a_acc, g_acc, n_b = carry['a_acc'], carry['g_acc'], carry['n_b']
        if self.data_axes:
            sgrads = jax.tree_util.tree_map(
                lambda x: jax.lax.psum(x, self.data_axes), sgrads
            )
            a_acc = {
                k: jax.lax.psum(x, self.data_axes) for k, x in a_acc.items()
            }
            g_acc = {
                k: jax.lax.psum(x, self.data_axes) for k, x in g_acc.items()
            }
            n_b = jax.lax.psum(n_b, self.data_axes)
        xbar = jax.lax.psum(carry['xbar'], PIPE_AXIS)
        tick_counts = carry['ticks']
        if self.data_axes:
            # every dp replica of a pipe rank counted the same schedule;
            # pmax collapses the data axes without inflating the counts
            tick_counts = jax.lax.pmax(tick_counts, self.data_axes)
        return (
            loss_sum, sgrads, hgrads, lgrads, a_acc, g_acc, n_b, xbar,
            tick_counts[None],
        )

    # ------------------------------------------------------------- loss

    def loss_and_stats(self, params, batch):
        """(loss, grads, chunk-stacked stats) from the single-slot scan."""
        loss, grads, stats, _ = self.loss_stats_and_ticks(params, batch)
        return loss, grads, stats

    def loss_stats_and_ticks(self, params, batch):
        """:meth:`loss_and_stats` plus the per-rank ``(p, 3)`` int32
        tick counters ``(executed F, executed B, idle)`` surfaced from
        the scan carry — the runtime ground truth
        :meth:`tick_report` diffs against the schedule tables."""
        tokens, targets = batch
        b, s = tokens.shape
        m = self.n_microbatches
        self._validate_batch(b)
        if m % self.p_ranks != 0:
            raise ValueError(
                f'n_microbatches ({m}) must be a multiple of the pipeline '
                f'rank count ({self.p_ranks}) for interleaving'
            )
        gstats0 = self.zero_gstats()

        def embed_fn(ep):
            x = self._embed({'embed': ep['embed'],
                             'pos_embed': ep['pos_embed']}, tokens)
            return x.reshape(m, b // m, s, self.d_model)

        epar = {'embed': params['embed'], 'pos_embed': params['pos_embed']}
        x_feed, embed_pull = jax.vjp(embed_fn, epar)
        t_feed = targets.reshape(m, b // m, s)

        gspec = {k: P(PIPE_AXIS) for k in gstats0}
        bspec = P(None, self.data_axes) if self.data_axes else P()
        out = jax.shard_map(
            self._body_interleaved,
            mesh=self.mesh,
            axis_names=self._manual,
            in_specs=(P(PIPE_AXIS), P(), P(), bspec, bspec, gspec),
            out_specs=(
                P(),
                jax.tree_util.tree_map(lambda _: P(PIPE_AXIS),
                                       params['stages']),
                P(),
                P(),
                {k: P(PIPE_AXIS) for k in gstats0},
                {k: P(PIPE_AXIS) for k in gstats0},
                P(PIPE_AXIS),
                bspec,
                P(PIPE_AXIS),
            ),
        )(params['stages'], params['head'], params['ln_f'], x_feed, t_feed,
          gstats0)
        (loss, sgrads, hgrads, lgrads, a_stats, g_stats, counts, xbar,
         tick_counts) = out
        (egrads,) = embed_pull(xbar)
        grads = {
            'embed': egrads['embed'],
            'pos_embed': egrads['pos_embed'],
            'stages': sgrads,
            'head': hgrads,
            'ln_f': lgrads,
        }
        denom = jnp.maximum(counts, 1.0)
        a_avg = {k: x / denom[:, None, None] for k, x in a_stats.items()}
        g_avg = {k: x / denom[:, None, None] for k, x in g_stats.items()}
        return (
            loss, grads, capture_lib.CapturedStats(a=a_avg, g=g_avg),
            tick_counts,
        )

    # ------------------------------------------------------------ report

    def tick_report(self, tick_counts=None):
        """``comms_report()``-style schedule accounting for this model.

        The ``predicted`` block comes from the static schedule tables
        (exact per-rank F/B/idle slot counts and the simulator's
        :meth:`~kfac_tpu.parallel.interleaved.SingleSlotSchedule.bubble_slots`);
        pass the counters returned by :meth:`loss_stats_and_ticks` as
        ``tick_counts`` to fold in the EXECUTED counts and the
        ``matches_schedule`` verdict.
        """
        import numpy as np

        sched = self._sched
        kinds = np.asarray(sched.ops)[:, :, 0]
        predicted = np.stack(
            [(kinds == 0).sum(0), (kinds == 1).sum(0), (kinds < 0).sum(0)],
            axis=1,
        )
        p = self.p_ranks
        out = {
            'schedule': self.schedule,
            'p_ranks': p,
            'virtual_chunks': self.virtual_chunks,
            'n_microbatches': self.n_microbatches,
            'ticks': int(sched.ticks),
            'bubble_slots': int(sched.bubble_slots()),
            'bubble_fraction': float(sched.bubble_slots())
            / float(sched.ticks * p),
            'predicted': {
                'executed_f': predicted[:, 0].tolist(),
                'executed_b': predicted[:, 1].tolist(),
                'idle': predicted[:, 2].tolist(),
            },
        }
        if tick_counts is not None:
            executed = np.asarray(tick_counts)
            out['executed'] = {
                'executed_f': executed[:, 0].tolist(),
                'executed_b': executed[:, 1].tolist(),
                'idle': executed[:, 2].tolist(),
            }
            out['matches_schedule'] = bool((executed == predicted).all())
        return out
