"""Distributed execution: meshes, collectives, KAISA sharded engine."""

from kfac_tpu.parallel import collectives, mesh
from kfac_tpu.parallel.kaisa import DistKFACState, DistributedKFAC, build_buckets
from kfac_tpu.parallel.mesh import batch_sharding, kaisa_mesh, replicated

__all__ = [
    'DistKFACState',
    'DistributedKFAC',
    'batch_sharding',
    'build_buckets',
    'collectives',
    'kaisa_mesh',
    'mesh',
    'replicated',
]
