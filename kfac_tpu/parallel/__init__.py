"""Distributed execution: meshes, collectives, KAISA/TP/CP/PP engines."""

from kfac_tpu.parallel import (
    collectives,
    expert_parallel,
    mesh,
    pipeline,
    tensor_parallel,
)
from kfac_tpu.parallel.expert_parallel import (
    EPSwitchFFN,
    combined_value_stats_and_grad,
)
from kfac_tpu.parallel.interleaved_scan import InterleavedPipelinedLM
from kfac_tpu.parallel.kaisa import DistKFACState, DistributedKFAC, build_buckets
from kfac_tpu.parallel.mesh import (
    batch_sharding,
    kaisa_mesh,
    pipeline_mesh,
    replicated,
    token_sharding,
    train_mesh,
)
from kfac_tpu.parallel.pipeline import PipelinedLM, PipelineKFAC

__all__ = [
    'DistKFACState',
    'DistributedKFAC',
    'EPSwitchFFN',
    'InterleavedPipelinedLM',
    'PipelineKFAC',
    'PipelinedLM',
    'batch_sharding',
    'build_buckets',
    'collectives',
    'combined_value_stats_and_grad',
    'expert_parallel',
    'kaisa_mesh',
    'mesh',
    'pipeline',
    'pipeline_mesh',
    'replicated',
    'tensor_parallel',
    'token_sharding',
    'train_mesh',
]
