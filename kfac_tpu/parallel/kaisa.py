"""KAISA distributed execution: sharded second-order work on a device mesh.

The reference expresses KAISA imperatively — per-rank ``if rank ==
inv_worker`` branches, explicit broadcasts, NCCL groups
(kfac/base_preconditioner.py:310-382, kfac/assignment.py:121-225). That
shape is anti-SPMD: under XLA every device runs one traced program. Here the
same strategy space is expressed as *data layout*:

- Per-layer factors are stacked into shape buckets ``(L, d, d)`` — batched
  eigh and batched preconditioning keep the MXU busy instead of launching
  per-layer kernels.
- The stacked layer axis is sharded over the whole mesh for the
  eigendecomposition (every device decomposes its assigned slice — the
  greedy assignment's load balance, kfac/assignment.py:227-319, degenerates
  to round-robin because bucket entries are shape-uniform).
- Decompositions are then resharded to the strategy's resident layout:
  replicated for COMM-OPT (the "inverse broadcast"), sharded over the column
  axis for HYBRID/MEM-OPT. Preconditioned gradients are computed under that
  layout and resharded to replicated (the "gradient broadcast"). XLA inserts
  exactly the all-gathers KAISA prescribes; grad_worker_fraction is the mesh
  aspect ratio (kfac_tpu/assignment.py:mesh_shape).

Memory matches the strategy: MEM-OPT keeps 1/world of the second-order state
per device, COMM-OPT replicates it — the same trade the gradient worker
fraction buys in the reference (kfac/enums.py:40-54).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from kfac_tpu import assignment as assignment_lib
from kfac_tpu import enums
from kfac_tpu import health as health_lib
from kfac_tpu import tracing
from kfac_tpu.async_inverse import host as async_host
from kfac_tpu.async_inverse import sliced as async_sliced
from kfac_tpu.async_inverse import slots as async_slots
from kfac_tpu.compression import offload as offload_lib
from kfac_tpu.compression import quant as quant_lib
from kfac_tpu.layers import capture as capture_lib
from kfac_tpu.layers import registry as registry_lib
from kfac_tpu.observability import comms as comms_lib
from kfac_tpu.observability import compile_watch as compile_watch_lib
from kfac_tpu.observability import flight_recorder as flight_lib
from kfac_tpu.observability import metrics as metrics_lib
from kfac_tpu.ops import factors as factors_lib
from kfac_tpu.parallel import collectives
from kfac_tpu.parallel import mesh as mesh_lib
from kfac_tpu.preconditioner import KFACPreconditioner, _resolve


def size_class(d: int, granularity: int) -> int:
    """Round a factor dimension up to its size class.

    Execution-side load balancing for heterogeneous factor shapes: a
    ResNet-50 has dozens of distinct conv factor dims, often 1-2 layers
    each; bucketing by EXACT dims turns the inverse update into dozens of
    sequential mostly-padding batched decompositions. Rounding dims into a
    few classes collapses them so one batched decomposition spans layers of
    different true sizes — the role the reference's greedy cost-model
    assignment plays (kfac/assignment.py:227-319), solved shape-side for
    XLA's static-shape world. Padding is mathematically exact: factors pad
    with an identity block (decoupled eigenspace), gradients with zeros
    (see ``pad_factor``/``pad_grad``).

    ``granularity <= 1`` disables classing (exact dims). Dims below the
    granularity round to the next power of two (>= 8), capped at the
    granularity, so tiny layers don't pay a full-class decomposition (the
    cap matters for non-power-of-two granularities, where the next power
    of two could overshoot the class a d >= granularity dim would get);
    larger dims round to the next multiple of the granularity (MXU-tile
    friendly).
    """
    if granularity <= 1 or d == 0:
        return d
    if d >= granularity:
        return -(-d // granularity) * granularity
    c = 8
    while c < d:
        c *= 2
    return min(c, granularity)


def pad_factor(m: jax.Array, c: int) -> jax.Array:
    """Embed a (d, d) factor into its (c, c) class slot, identity block in
    the padding. blockdiag(A, I) has a decoupled unit eigenspace, and the
    matching gradient rows/cols are zero, so eigen/inverse preconditioning
    of the real block is unchanged (basis-invariance of matrix functions)."""
    d = m.shape[0]
    if d == c:
        return m
    out = jnp.zeros((c, c), m.dtype).at[:d, :d].set(m)
    idx = jnp.arange(d, c)
    return out.at[idx, idx].set(jnp.ones((c - d,), m.dtype))


def pad_grad(m: jax.Array, cg: int, ca: int) -> jax.Array:
    """Zero-pad a (dg, da) gradient matrix into its (cg, ca) class slot."""
    if m.shape == (cg, ca):
        return m
    return jnp.zeros((cg, ca), m.dtype).at[: m.shape[0], : m.shape[1]].set(m)


class Bucket(NamedTuple):
    """Layers sharing factor size classes, stacked along a leading slot
    axis. ``da``/``dg`` are CLASS dims; ``dims`` carries each layer's true
    (da, dg) for grad embedding/extraction."""

    key: str
    layers: tuple[str, ...]
    da: int
    dg: int
    padded: int  # slots incl. padding to a multiple of world size
    dims: tuple[tuple[int, int], ...]


def build_buckets(
    registry: registry_lib.Registry, world: int, granularity: int = 128
) -> list[Bucket]:
    """Group registered layers by (A class, G class), pad to the world
    size."""
    groups: dict[tuple[int, int], list[tuple[str, int, int]]] = {}
    for name, h in registry.layers.items():
        da, dg = h.a_factor_shape[0], h.g_factor_shape[0]
        key = (size_class(da, granularity), size_class(dg, granularity))
        groups.setdefault(key, []).append((name, da, dg))
    buckets = []
    for (ca, cg), rows in sorted(groups.items()):
        n = len(rows)
        padded = -(-n // world) * world
        buckets.append(
            Bucket(
                key=f'{ca}x{cg}',
                layers=tuple(r[0] for r in rows),
                da=ca,
                dg=cg,
                padded=padded,
                dims=tuple((r[1], r[2]) for r in rows),
            )
        )
    return buckets


class StorageBucket(NamedTuple):
    """One side's (A or G) factor storage: layers stacked along slots.

    With ``colocate_factors=True`` these mirror the (da, dg) pair buckets,
    so a layer's A and G share a slot index (same owning device). With
    ``False`` each side groups by its own dimension only — A and G of one
    layer can land in different stacks/slots, splitting its two
    eigendecompositions across devices (reference
    kfac/assignment.py:268-304).
    """

    key: str
    layers: tuple[str, ...]
    d: int  # class dim
    padded: int
    dims: tuple[int, ...]  # true per-layer dims


def build_side_buckets(
    registry: registry_lib.Registry,
    world: int,
    side: str,
    granularity: int = 128,
) -> list[StorageBucket]:
    """Group layers by a single factor size class (non-colocated
    storage)."""
    groups: dict[int, list[tuple[str, int]]] = {}
    for name, h in registry.layers.items():
        d = h.a_factor_shape[0] if side == 'a' else h.g_factor_shape[0]
        groups.setdefault(size_class(d, granularity), []).append((name, d))
    return [
        StorageBucket(
            key=f'{side}{c}',
            layers=tuple(r[0] for r in rows),
            d=c,
            padded=-(-len(rows) // world) * world,
            dims=tuple(r[1] for r in rows),
        )
        for c, rows in sorted(groups.items())
    ]


def build_stores(
    registry: registry_lib.Registry,
    total_devices: int,
    granularity: int,
    colocate: bool,
    buckets: list[Bucket],
) -> tuple[list[StorageBucket], list[StorageBucket]]:
    """Factor STORAGE layout (A store, G store) for a configuration.

    Colocated stores mirror the (da, dg) pair buckets (A and G share a
    slot/device); non-colocated stores bucket each side by its own
    dimension so a layer's two eigendecompositions can run on different
    devices (reference kfac/assignment.py:268-304). Pure host-side shape
    arithmetic — shared by ``DistributedKFAC.__post_init__`` and the
    autotuner's mesh-less ``StaticLayout`` (kfac_tpu/autotune/model.py)
    so the analytic cost model prices exactly the layout the engine
    would build.
    """
    if colocate:
        a_store = [
            StorageBucket(
                b.key, b.layers, b.da, b.padded,
                tuple(d[0] for d in b.dims),
            )
            for b in buckets
        ]
        g_store = [
            StorageBucket(
                b.key, b.layers, b.dg, b.padded,
                tuple(d[1] for d in b.dims),
            )
            for b in buckets
        ]
        return a_store, g_store
    return (
        build_side_buckets(registry, total_devices, 'a', granularity),
        build_side_buckets(registry, total_devices, 'g', granularity),
    )


class DistKFACState(NamedTuple):
    """Stacked K-FAC state: bucket key -> (L, d, d) arrays.

    ``inv_damping`` records the damping the RESIDENT decompositions were
    built with (schedules resolve per step, so it can differ from the
    current step's damping) — consumed by
    :meth:`DistributedKFAC.inverse_residuals` so quality monitoring
    measures the inverse against the system it actually solved. Derived
    state: recomputed with the decompositions, never checkpointed.

    ``health``: :class:`kfac_tpu.health.HealthState` counters when the
    numerical-health sentinel is enabled, else ``None``. Per-layer scalars
    (replicated — layout-independent, so the same counters ride the dense
    and stacked states and survive cross-layout checkpoint migration).

    ``metrics``: :class:`kfac_tpu.observability.MetricsState` per-layer
    telemetry when metrics are enabled, else ``None``. Like ``health``,
    layer-keyed replicated scalars — the same drained schema as the dense
    engine, layout-independent.

    ``flight``: :class:`kfac_tpu.observability.FlightRecorderState`
    rolling telemetry ring when the flight recorder is enabled, else
    ``None``. Replicated (small fixed-size buffers, layout-independent);
    same ephemeral contract as ``metrics``.
    """

    step: jax.Array
    a: dict[str, jax.Array]
    g: dict[str, jax.Array]
    qa: dict[str, jax.Array]
    qg: dict[str, jax.Array]
    da: dict[str, jax.Array]
    dg: dict[str, jax.Array]
    dgda: dict[str, jax.Array]
    a_inv: dict[str, jax.Array]
    g_inv: dict[str, jax.Array]
    inv_damping: jax.Array
    health: Any = None
    metrics: Any = None
    flight: Any = None
    # double-buffered shadow decomposition slots when async_inverse mode
    # 'sliced' is enabled (kfac_tpu/async_inverse); ephemeral like
    # metrics/flight — a restore rematerializes and resets it
    shadow: Any = None
    # per-chunk error-feedback residuals ('c0', 'c1', ...) of the
    # compressed stat transport when stat_compression.error_feedback is
    # on, else None. DURABLE (unlike shadow): the residual is deferred
    # factor mass — dropping it at a restore would bias the next EMA by
    # exactly the noise error feedback exists to cancel. Float32,
    # replicated, shaped by the host-side chunk plan
    # (``_plan_compression``).
    comp_ef: Any = None


@dataclasses.dataclass
class DistributedKFAC:
    """KAISA preconditioning over a ``kaisa_mesh``.

    Args:
        config: hyperparameter/config carrier (cadences, damping, decay,
            kl_clip, lr, compute_method, dtypes are read from it).
        mesh: mesh from :func:`kfac_tpu.parallel.mesh.kaisa_mesh`; its shape
            encodes the gradient worker fraction. ``None`` builds the
            default COMM-OPT mesh — or the tuned plan's mesh when
            ``auto_layout`` applies.
        auto_layout: a :class:`kfac_tpu.autotune.TunedPlan` (or a path to
            one) from ``tools/kfac_tune.py``. When its topology+model
            fingerprint matches this process, the plan's knobs override
            the config's layout fields and, if no ``mesh`` was given, the
            plan's gradient-worker fraction picks the mesh; on a mismatch
            the plan is ignored with a rate-limited
            :class:`~kfac_tpu.warnings.LayoutPlanWarning`.
    """

    # Entry points the IR analyzer (kfac_tpu/analysis/ir) traces to
    # jaxprs; IR_STEP_PATH marks the per-step critical path (KFL204).
    # Unannotated on purpose: class constants, not dataclass fields.
    IR_ENTRY_POINTS = (
        'update_factors', 'update_inverses', 'precondition', 'step',
    )
    IR_STEP_PATH = ('step',)

    config: KFACPreconditioner
    mesh: Any = None
    auto_layout: Any = None

    def __post_init__(self) -> None:
        if self.auto_layout is not None:
            from kfac_tpu.autotune import plan as plan_lib

            self.config, self.mesh, self.auto_layout_applied = (
                plan_lib.resolve_auto_layout(
                    self.config, self.mesh, self.auto_layout
                )
            )
        else:
            self.auto_layout_applied = False
        if self.mesh is None:
            self.mesh = mesh_lib.kaisa_mesh()
        self.registry = self.config.registry
        # The KAISA strategy grid is the data-parallel mesh portion, but the
        # eigendecomposition work and factor storage shard over EVERY mesh
        # axis — model/seq-parallel devices pull their weight too.
        self.world = mesh_lib.grad_workers(self.mesh) * mesh_lib.n_cols(self.mesh)
        self.grad_workers = mesh_lib.grad_workers(self.mesh)
        self.all_axes = tuple(self.mesh.axis_names)
        self.total_devices = int(self.mesh.devices.size)
        self.strategy = assignment_lib.strategy_for_fraction(
            self.world, self.grad_workers / self.world
        )
        # resolved (never None) by KFACPreconditioner.__post_init__
        self.granularity = int(self.config.bucket_granularity)
        self.buckets = build_buckets(
            self.registry, self.total_devices, self.granularity
        )
        self.colocate = bool(self.config.colocate_factors)
        # Parity object: cost-model view of the placement for reporting and
        # for API compatibility with the reference's query surface (also
        # enforces MEM-OPT => colocated, as the reference does).
        self.assignment = assignment_lib.KAISAAssignment(
            assignment_lib.compute_work_costs(self.registry.layers),
            world_size=self.world,
            grad_worker_fraction=self.grad_workers / self.world,
            colocate_factors=self.colocate,
        )
        self.a_store, self.g_store = build_stores(
            self.registry, self.total_devices, self.granularity,
            self.colocate, self.buckets,
        )
        self._a_slot = {
            n: (sb.key, i)
            for sb in self.a_store
            for i, n in enumerate(sb.layers)
        }
        self._g_slot = {
            n: (sb.key, i)
            for sb in self.g_store
            for i, n in enumerate(sb.layers)
        }
        self._eigen = self.config.compute_method == enums.ComputeMethod.EIGEN
        self._prediv = self._eigen and self.config.prediv_eigenvalues
        if self._prediv and not self.colocate:
            raise NotImplementedError(
                'prediv_eigenvalues stores the fused per-layer eigenvalue '
                'grid, which requires colocate_factors=True'
            )
        if self.config.prediv_eigenvalues and not self._eigen:
            import warnings as _warnings

            _warnings.warn(
                'prediv_eigenvalues has no effect with the INVERSE compute '
                'method; ignoring',
                stacklevel=2,
            )
        # inverse_solver='auto' is served by
        # factors.batched_damped_inverse_auto: one scalar runtime cond per
        # device-local block, so the batched Cholesky runs only when some
        # slot's Newton-Schulz residual fails (it used to be a vmapped
        # per-slot cond -> select paying both branches unconditionally,
        # which warranted a TPUPerformanceWarning here).
        self._plan_async()
        self._plan_compression()
        self._plan_offload()

    def _plan_compression(self) -> None:
        """Precompute the host-side chunk plan of the compressed stat
        transport (exact mirror of the runtime packing in
        ``_stack_stats``: A-store rows then G-store rows through
        ``collectives.plan_chunks`` with the same byte cap), so error-
        feedback residual shapes are known without tracing a step."""
        ccfg = self.config.stat_compression
        self._compression = ccfg
        self._comp_plan = None
        if ccfg is None:
            return
        cfg = self.config
        specs = [
            (sb.d * (sb.d + 1) // 2, jnp.dtype(cfg.factor_dtype))
            for store in (self.a_store, self.g_store)
            for sb in store
            for _ in sb.layers
        ]
        cap = cfg.allreduce_bucket_cap_mb
        self._comp_plan = collectives.plan_chunks(
            specs, max_bytes=None if cap is None else cap * 1e6
        )

    def _plan_offload(self) -> None:
        """Attach the cold-factor offload manager (host-side state only;
        config validation lives in KFACPreconditioner.__post_init__)."""
        self._offload_manager = (
            None if self.config.offload is None
            else offload_lib.OffloadManager(self)
        )

    def _plan_async(self) -> None:
        """Precompute the async refresh plan over the STACKED layout
        (units are storage buckets — one sharded batched decomposition per
        slice — not layers; same attribute surface as the dense engine's
        ``_plan_async``)."""
        acfg = self.config.async_inverse
        self._async_mode = None if acfg is None else acfg.mode
        self._async_worker = None
        self._async_apply_cache = None
        if acfg is None:
            return
        self._async_n_steps = int(self.config.inv_update_steps)
        if acfg.mode == 'sliced':
            units = async_sliced.kaisa_units(self)
            n = min(self._async_n_steps, acfg.max_slices or len(units))
            self._async_slices = async_slots.plan_slices(units, n)
            self._async_n_slices = len(self._async_slices)

    # ------------------------------------------------------------ shardings

    def _factor_spec(self) -> P:
        """Factors live sharded over every mesh axis (their only consumer is
        the device that decomposes them)."""
        return P(self.all_axes)

    def _decomp_spec(self) -> P:
        """Resident layout of decompositions: the KAISA strategy knob."""
        if self.strategy == enums.DistributedStrategy.COMM_OPT:
            return P()  # replicated == inverses broadcast to all grad workers
        return P(mesh_lib.COL_AXIS)  # sharded by column == HYBRID/MEM-OPT

    def state_shardings(self) -> Any:
        """NamedSharding pytree for :class:`DistKFACState` (for jit
        in_shardings / donation)."""
        fac = NamedSharding(self.mesh, self._factor_spec())
        dec = NamedSharding(self.mesh, self._decomp_spec())
        rep = NamedSharding(self.mesh, P())

        def adict(sh):
            return {sb.key: sh for sb in self.a_store}

        def gdict(sh):
            return {sb.key: sh for sb in self.g_store}

        eigen = self._eigen
        if self.config.health is not None:
            names = list(self.registry.layers)
            health_sh = health_lib.HealthState(
                skipped_steps=rep,
                damping_mult={n: rep for n in names},
                quarantined={n: rep for n in names},
                bad_inv={n: rep for n in names},
                quarantine_events={n: rep for n in names},
            )
        else:
            health_sh = None
        if self.config.metrics is not None:
            names = tuple(self.registry.layers)
            metrics_sh = metrics_lib.MetricsState(
                names=names,
                keys=tuple(metrics_lib.metric_keys(
                    self.config.metrics, list(names))),
                last_factor_step=rep,
                last_inv_step=rep,
                scalars=rep,
            )
        else:
            metrics_sh = None
        if self.config.flight is not None:
            keys = tuple(metrics_lib.metric_keys(
                self.config.metrics, list(self.registry.layers)))
            flight_sh = flight_lib.FlightRecorderState(
                keys=keys,
                steps=rep,
                loss=rep,
                loss_valid=rep,
                grad_norm=rep,
                scalars=rep,
            )
        else:
            flight_sh = None
        if self._async_mode == 'sliced':
            from kfac_tpu.async_inverse import slots as _slots

            shadow_sh = _slots.ShadowSlots(
                qa=adict(dec) if eigen else {},
                qg=gdict(dec) if eigen else {},
                da=adict(dec) if eigen and not self._prediv else {},
                dg=gdict(dec) if eigen and not self._prediv else {},
                dgda=(
                    {b.key: dec for b in self.buckets}
                    if self._prediv else {}
                ),
                a_inv={} if eigen else adict(dec),
                g_inv={} if eigen else gdict(dec),
                progress=rep,
                damping=rep,
            )
        else:
            shadow_sh = None
        if self._compression is not None and self._compression.error_feedback:
            comp_ef_sh = {
                f'c{i}': rep for i in range(len(self._comp_plan))
            }
        else:
            comp_ef_sh = None
        return DistKFACState(
            step=rep,
            a=adict(fac),
            g=gdict(fac),
            qa=adict(dec) if eigen else {},
            qg=gdict(dec) if eigen else {},
            da=adict(dec) if eigen and not self._prediv else {},
            dg=gdict(dec) if eigen and not self._prediv else {},
            dgda={b.key: dec for b in self.buckets} if self._prediv else {},
            a_inv={} if eigen else adict(dec),
            g_inv={} if eigen else gdict(dec),
            inv_damping=rep,
            health=health_sh,
            metrics=metrics_sh,
            flight=flight_sh,
            shadow=shadow_sh,
            comp_ef=comp_ef_sh,
        )

    # ----------------------------------------------------------------- init

    def init(self) -> DistKFACState:
        """Allocate sharded stacked state (identity factors, zero decomps)."""

        def build() -> DistKFACState:
            cfg = self.config
            a, g, qa, qg, da, dg, dgda, a_inv, g_inv = ({} for _ in range(9))
            for sb in self.a_store:
                a[sb.key] = jnp.broadcast_to(
                    jnp.eye(sb.d, dtype=cfg.factor_dtype),
                    (sb.padded, sb.d, sb.d),
                )
                if self._eigen:
                    qa[sb.key] = jnp.zeros(
                        (sb.padded, sb.d, sb.d), cfg.inv_dtype
                    )
                    if not self._prediv:
                        da[sb.key] = jnp.zeros((sb.padded, sb.d), cfg.inv_dtype)
                else:
                    a_inv[sb.key] = jnp.zeros(
                        (sb.padded, sb.d, sb.d), cfg.inv_dtype
                    )
            for sb in self.g_store:
                g[sb.key] = jnp.broadcast_to(
                    jnp.eye(sb.d, dtype=cfg.factor_dtype),
                    (sb.padded, sb.d, sb.d),
                )
                if self._eigen:
                    qg[sb.key] = jnp.zeros(
                        (sb.padded, sb.d, sb.d), cfg.inv_dtype
                    )
                    if not self._prediv:
                        dg[sb.key] = jnp.zeros((sb.padded, sb.d), cfg.inv_dtype)
                else:
                    g_inv[sb.key] = jnp.zeros(
                        (sb.padded, sb.d, sb.d), cfg.inv_dtype
                    )
            if self._prediv:
                for b in self.buckets:
                    dgda[b.key] = jnp.zeros(
                        (b.padded, b.dg, b.da), cfg.inv_dtype
                    )
            if (
                self._compression is not None
                and self._compression.error_feedback
            ):
                comp_ef = {
                    f'c{i}': jnp.zeros((int(ch['elements']),), jnp.float32)
                    for i, ch in enumerate(self._comp_plan)
                }
            else:
                comp_ef = None
            return DistKFACState(
                step=jnp.asarray(0, jnp.int32),
                a=a, g=g, qa=qa, qg=qg, da=da, dg=dg, dgda=dgda,
                a_inv=a_inv, g_inv=g_inv,
                inv_damping=jnp.asarray(
                    _resolve(cfg.damping, jnp.asarray(0, jnp.int32)),
                    jnp.float32,
                ),
                health=(
                    health_lib.init_health(self.registry.layers)
                    if cfg.health is not None else None
                ),
                metrics=(
                    metrics_lib.init_metrics(
                        cfg.metrics, list(self.registry.layers)
                    )
                    if cfg.metrics is not None else None
                ),
                flight=(
                    flight_lib.init_flight(
                        cfg.flight,
                        metrics_lib.metric_keys(
                            cfg.metrics, list(self.registry.layers)
                        ),
                    )
                    if cfg.flight is not None else None
                ),
                comp_ef=comp_ef,
            )

        def build_with_shadow() -> DistKFACState:
            state = build()
            if self._async_mode == 'sliced':
                state = state._replace(
                    shadow=async_sliced.kaisa_shadow(self, state)
                )
            return state

        return jax.jit(
            build_with_shadow, out_shardings=self.state_shardings()
        )()

    # ------------------------------------------------------------- stacking

    def _stack_stats(
        self, state: DistKFACState, stats: capture_lib.CapturedStats
    ) -> tuple[dict[str, jax.Array], dict[str, jax.Array], Any]:
        """Stack per-layer stats into bucket layout.

        Registered layers absent from ``stats`` (not executed by this
        loss_fn) take their current state value, so the EMA leaves them
        unchanged — same semantics as the dense engine
        (kfac_tpu/preconditioner.py:update_factors) and the reference's
        hooks, which simply never fire for unexecuted modules.

        Returns ``(a_stacks, g_stacks, new_comp_ef)``: the third element
        is the updated error-feedback residual dict when the compressed
        transport carries one, else the state's ``comp_ef`` unchanged.
        """
        cfg = self.config
        bucketed = (
            cfg.allreduce_method == enums.AllreduceMethod.ALLREDUCE_BUCKETED
        )
        # Pin each captured factor to replicated BEFORE stacking: under
        # GSPMD the capture contraction can leave per-layer covariances with
        # inferred shardings over model/seq axes, and concatenating
        # mixed-sharding rows forces XLA's "involuntary full
        # rematerialization" (replicate the whole stack, then re-slice).
        # ALLREDUCE pins (all-gathers) each small (d, d) matrix on its own;
        # ALLREDUCE_BUCKETED packs the upper triangles of every factor into
        # one flat buffer and pins that — one large collective carrying
        # half the bytes (factors are symmetric), the reference's bucketed
        # symmetric transport (kfac/distributed.py:305-374, 422-465) for
        # DCN-bound multihost meshes.
        rep = NamedSharding(self.mesh, P())

        def pin(m):
            return m if bucketed else jax.lax.with_sharding_constraint(m, rep)

        def side_rows(store, side_stats, side_state):
            rows: dict[str, list] = {}
            for sb in store:
                r = []
                for i, n in enumerate(sb.layers):
                    if n in side_stats:
                        # embed the true-dim statistic into its size-class
                        # slot (identity padding — exact, see pad_factor)
                        r.append(
                            pad_factor(
                                pin(
                                    side_stats[n].astype(cfg.factor_dtype)
                                ),
                                sb.d,
                            )
                        )
                    else:
                        # state slices are factor-sharded — pin them too so
                        # the stack never mixes shardings (already
                        # class-size)
                        r.append(pin(side_state[sb.key][i]))
                rows[sb.key] = r
            return rows

        rows_a = side_rows(self.a_store, stats.a, state.a)
        rows_g = side_rows(self.g_store, stats.g, state.g)

        new_ef = getattr(state, 'comp_ef', None)
        if bucketed:
            flat_rows = [
                m for sb in self.a_store for m in rows_a[sb.key]
            ] + [m for sb in self.g_store for m in rows_g[sb.key]]
            tris = [collectives.get_triu(m) for m in flat_rows]
            # byte-capped chunks (reference 25 MB default): bounds the
            # transient pack footprint and the per-collective message size
            cap = cfg.allreduce_bucket_cap_mb
            packed = collectives.concat_flat_chunked(
                tris, max_bytes=None if cap is None else cap * 1e6
            )
            ccfg = self._compression
            if ccfg is None:
                chunks = [
                    (jax.lax.with_sharding_constraint(flat, rep), specs)
                    for flat, specs in packed
                ]
            else:
                # Quantize each flat chunk blockwise to the wire dtype and
                # pin the QUANTIZED payload + scales to replicated — the
                # sharding constraint IS the collective under GSPMD, so
                # this is what crosses the interconnect. Error feedback
                # adds the carried residual before quantizing and keeps
                # what the wire dropped for the next factor update.
                ef_in = new_ef
                ef_out: dict[str, jax.Array] = {}
                chunks = []
                for i, (flat, specs) in enumerate(packed):
                    key = f'c{i}'
                    carried = flat.astype(jnp.float32)
                    if ef_in is not None:
                        carried = carried + ef_in[key]
                    payload, scales = quant_lib.quantize_blockwise(
                        carried, ccfg.dtype, ccfg.block_size
                    )
                    payload = jax.lax.with_sharding_constraint(payload, rep)
                    scales = jax.lax.with_sharding_constraint(scales, rep)
                    deq = quant_lib.dequantize_blockwise(
                        payload, scales, flat.shape[0], ccfg.block_size
                    )
                    if ef_in is not None:
                        ef_out[key] = carried - deq
                    chunks.append((deq.astype(flat.dtype), specs))
                if ef_in is not None:
                    new_ef = ef_out
            unpacked = iter(
                collectives.fill_triu(m.shape, t)
                for m, t in zip(
                    flat_rows, collectives.split_flat_chunked(chunks)
                )
            )
            for sb in self.a_store:  # same order as flat_rows: a then g
                rows_a[sb.key] = [next(unpacked) for _ in rows_a[sb.key]]
            for sb in self.g_store:
                rows_g[sb.key] = [next(unpacked) for _ in rows_g[sb.key]]

        def stack_side(store, rows):
            stacks = {}
            for sb in store:
                r = rows[sb.key]
                pad = sb.padded - len(sb.layers)
                if pad:
                    r = r + [jnp.eye(sb.d, dtype=cfg.factor_dtype)] * pad
                stacks[sb.key] = jnp.stack(r)
            return stacks

        return (
            stack_side(self.a_store, rows_a),
            stack_side(self.g_store, rows_g),
            new_ef,
        )

    # --------------------------------------------------------------- health

    def _slot_mults(
        self, health, layers: tuple[str, ...], padded: int
    ) -> jax.Array:
        """(L,) per-slot damping multipliers for a stack's layers (padding
        slots at 1.0). Assembled by update-slice, not jnp.stack: GSPMD
        mispartitions stacks of replicated scalars on fractional
        grad-worker meshes (see the gstack note in ``precondition``)."""
        out = jnp.ones((padded,), jnp.float32)
        for i, n in enumerate(layers):
            out = out.at[i].set(health.damping_mult[n])
        return out

    def _slot_mask(
        self,
        flags: dict[str, jax.Array],
        layers: tuple[str, ...],
        padded: int,
    ) -> jax.Array | None:
        """(L,) bool from per-layer flags; layers without a flag (and
        padding slots) are False. None when no slot carries a flag.
        Update-slice assembly for the same reason as ``_slot_mults``."""
        if not any(n in flags for n in layers):
            return None
        out = jnp.zeros((padded,), bool)
        for i, n in enumerate(layers):
            if n in flags:
                out = out.at[i].set(flags[n])
        return out

    # ------------------------------------------------------- factor updates

    @tracing.scope('dist_kfac.update_factors')
    def update_factors(
        self, state: DistKFACState, stats: capture_lib.CapturedStats
    ) -> DistKFACState:
        """EMA update on the stacked factors (sharded, local per device).

        Statistics arrive already global-batch-averaged (the covariance
        contraction under pjit psums over the data-sharded row axis — the
        reference's explicit factor allreduce, kfac/layers/base.py:282-336).
        """
        alpha = _resolve(self.config.factor_decay, state.step)
        a_stacks, g_stacks, new_ef = self._stack_stats(state, stats)
        fac = NamedSharding(self.mesh, self._factor_spec())
        # Capture weights (routed MoE layers): per-slot effective decay
        # alpha_eff = 1 - (1-alpha)*w so the EMA moves proportionally to
        # the evidence each layer's capture carried. Slots without a
        # weight (ordinary layers, unexecuted layers — whose stacked stat
        # is their own state value — and size-class padding) use w=1,
        # which reduces exactly to the unweighted update.
        weights = getattr(stats, 'w', None) or {}

        def slot_alphas(store_bucket):
            if not any(
                n in weights and n in stats.a for n in store_bucket.layers
            ):
                return None
            w = [
                weights[n] if (n in weights and n in stats.a)
                else jnp.float32(1.0)
                for n in store_bucket.layers
            ]
            w += [jnp.float32(1.0)] * (store_bucket.padded - len(w))
            return factors_lib.effective_alpha(alpha, jnp.stack(w))

        def ema(store, side_state, stacks):
            out = {}
            for sb in store:
                s = jax.lax.with_sharding_constraint(stacks[sb.key], fac)
                av = slot_alphas(sb)
                if av is None:
                    out[sb.key] = alpha * side_state[sb.key] + (1 - alpha) * s
                else:
                    av = av[:, None, None].astype(s.dtype)
                    out[sb.key] = av * side_state[sb.key] + (1 - av) * s
            return out

        new_a = ema(self.a_store, state.a, a_stacks)
        new_g = ema(self.g_store, state.g, g_stacks)
        updated = set(stats.a) | set(stats.g)
        ok: dict[str, jax.Array] = {}
        new_health = state.health
        if self.config.health is not None:
            # factor quarantine, stacked form: one batched verdict per
            # storage bucket (finite + Gershgorin at each slot's effective
            # damping), combined per LAYER across its A and G slots so both
            # factors roll back together — same semantics as the dense
            # engine's per-layer loop
            # (kfac_tpu/preconditioner.py:update_factors). Layers absent
            # from this capture get no verdict (their stacked stat is their
            # own state value — the EMA left them unchanged).
            hc = self.config.health
            h = state.health
            damping = _resolve(self.config.damping, state.step)

            def verdicts(store, stacks):
                return {
                    sb.key: health_lib.factor_ok(
                        stacks[sb.key],
                        damping * self._slot_mults(h, sb.layers, sb.padded),
                        hc.quarantine_threshold,
                    )
                    for sb in store
                }

            ok_a = verdicts(self.a_store, new_a)
            ok_g = verdicts(self.g_store, new_g)
            for n in self.registry.layers:
                if n not in updated:
                    continue
                ak, ai = self._a_slot[n]
                gk, gi = self._g_slot[n]
                ok[n] = ok_a[ak][ai] & ok_g[gk][gi]
            roll = {n: ~v for n, v in ok.items()}

            def rollback(store, old, new):
                out = {}
                for sb in store:
                    mask = self._slot_mask(roll, sb.layers, sb.padded)
                    out[sb.key] = (
                        new[sb.key] if mask is None
                        else jnp.where(
                            mask[:, None, None], old[sb.key], new[sb.key]
                        )
                    )
                return out

            mult = dict(h.damping_mult)
            quarantined = dict(h.quarantined)
            events = dict(h.quarantine_events)
            for n, okn in ok.items():
                mult[n], quarantined[n], events[n] = (
                    health_lib.quarantine_update(
                        hc, okn, h.damping_mult[n], h.quarantined[n],
                        h.quarantine_events[n],
                    )
                )
            new_a = rollback(self.a_store, state.a, new_a)
            new_g = rollback(self.g_store, state.g, new_g)
            new_health = h._replace(
                damping_mult=mult, quarantined=quarantined,
                quarantine_events=events,
            )
        state = state._replace(
            a=new_a, g=new_g, health=new_health, comp_ef=new_ef
        )
        if self.config.metrics is not None and state.metrics is not None:
            state = state._replace(
                metrics=self._record_factor_metrics(state, updated, ok)
            )
        return state

    def _record_factor_metrics(
        self,
        state: DistKFACState,
        updated: set[str],
        ok_verdicts: dict[str, jax.Array],
    ) -> metrics_lib.MetricsState:
        """Factor-phase telemetry from the post-rollback stacked factors.

        Gershgorin bounds are taken on each layer's TRUE-dim block sliced
        out of its class slot (the identity padding would otherwise clamp
        both bounds toward 1), giving exact value parity with the dense
        engine's per-layer bounds.
        """
        mcfg = self.config.metrics
        ms = state.metrics
        scalars: dict[str, jax.Array] = {}
        touched: dict[str, jax.Array | None] = {}
        for n, helper in self.registry.layers.items():
            if n not in updated:
                continue
            if mcfg.factor_bounds:
                ak, ai = self._a_slot[n]
                gk, gi = self._g_slot[n]
                da = helper.a_factor_shape[0]
                dg = helper.g_factor_shape[0]
                lmin_a, lmax_a = metrics_lib.gershgorin_bounds(
                    state.a[ak][ai, :da, :da])
                lmin_g, lmax_g = metrics_lib.gershgorin_bounds(
                    state.g[gk][gi, :dg, :dg])
                scalars[f'factor_lmin/a/{n}'] = lmin_a
                scalars[f'factor_lmax/a/{n}'] = lmax_a
                scalars[f'factor_lmin/g/{n}'] = lmin_g
                scalars[f'factor_lmax/g/{n}'] = lmax_g
            touched[n] = ok_verdicts.get(n)
        return metrics_lib.update_scalars(ms, scalars)._replace(
            last_factor_step=metrics_lib.advance_last(
                ms.last_factor_step, ms.names, touched, state.step))

    # ------------------------------------------------------------- inverses

    def _sharded_eigh(self, stack: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Batched eigh with the slot axis sharded over the full mesh.

        shard_map guarantees each device decomposes only its slice — the
        SPMD realization of per-rank ``compute_a_inv`` work division
        (reference kfac/base_preconditioner.py:341-343).
        """

        def local(block):
            d, q = factors_lib.batched_eigh(
                block, self.config.eigh_impl
            )
            return q, jnp.clip(d, 0.0)

        spec = P(self.all_axes)
        q, d = jax.shard_map(
            local,
            mesh=self.mesh,
            in_specs=spec,
            out_specs=(spec, spec),
        )(stack)
        return q, d

    def _sharded_inv(
        self, stack: jax.Array, damping, prev: jax.Array | None = None
    ) -> jax.Array:
        """Batched sharded damped inverse; ``prev`` (the resident inverse
        stack) warm-starts Newton-Schulz per slot — safeguarded inside
        the solver, so a fresh state's zero inverses cold-start.
        ``damping`` may be a scalar or a per-slot (L,) vector (per-layer
        escalated damping under factor quarantine) — the vector rides the
        shard_map with the same slot sharding as the stack."""
        dmp = jnp.broadcast_to(
            jnp.asarray(damping, jnp.float32), stack.shape[:1]
        )

        def local(block, prev_block, dmp_block):
            if self.config.inverse_solver == 'auto':
                # one scalar cond per device-local block: Cholesky runs
                # at runtime only when some slot's NS residual fails —
                # not the vmapped per-slot cond that lowers to a
                # pay-both-branches select
                return factors_lib.batched_damped_inverse_auto(
                    block, dmp_block, jnp.float32,
                    self.config.newton_schulz_iters, x0=prev_block,
                )
            return jax.vmap(
                lambda m, w, dm: factors_lib.damped_inverse(
                    m, dm, jnp.float32, self.config.inverse_solver,
                    self.config.newton_schulz_iters, x0=w,
                )
            )(block, prev_block, dmp_block)

        if prev is None:
            prev = jnp.zeros_like(stack)
        spec = P(self.all_axes)
        # prev stays in its own dtype (inv_dtype, typically f32): casting
        # to a bf16 factor dtype would inflate the warm residual by
        # eps_bf16 * kappa and reject the warm start exactly in the
        # high-kappa regime where it saves the most
        # check_vma=False: the NS solver's convergence while_loop has no
        # replication rule on some installs; the body is forward-only
        # (never differentiated), so the check buys nothing here.
        return jax.shard_map(
            local, mesh=self.mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False,
        )(stack, prev, dmp)

    @tracing.scope('dist_kfac.update_inverses')
    def update_inverses(self, state: DistKFACState) -> DistKFACState:
        cfg = self.config
        hc = cfg.health
        h = state.health
        damping = _resolve(cfg.damping, state.step)
        dec = NamedSharding(self.mesh, self._decomp_spec())
        # per-slot verdicts on this refresh's outputs, per storage bucket —
        # combined per layer below into the degradation counter
        ok_a_slots: dict[str, jax.Array] = {}
        ok_g_slots: dict[str, jax.Array] = {}
        ok_fused: dict[str, jax.Array] = {}

        def slot_damping(layers, padded):
            if hc is None:
                return damping
            return damping * self._slot_mults(h, layers, padded)

        if self._eigen:
            qa, qg, da, dg, dgda = {}, {}, {}, {}, {}
            # Reshard to the strategy's resident layout: XLA inserts the
            # KAISA inverse "broadcast" (all-gather over gw, or over the
            # world for COMM-OPT) at these constraints. With
            # colocate_factors=False the A and G loops run over different
            # stacks — a layer's two eigendecompositions land on whichever
            # devices own their side's slots.
            d_a_by_key, d_g_by_key = {}, {}

            def side(store, side_state, prev_q, prev_d, q_out, d_out,
                     d_by_key, ok_slots):
                for sb in store:
                    q_, d_ = self._sharded_eigh(side_state[sb.key])
                    qc = q_.astype(cfg.inv_dtype)
                    if hc is not None:
                        okv = jnp.isfinite(q_).all(axis=(-2, -1)) & jnp.isfinite(
                            d_
                        ).all(axis=-1)
                        ok_slots[sb.key] = okv
                        # non-finite decomposition: keep the previous one
                        qc = jnp.where(okv[:, None, None], qc, prev_q[sb.key])
                    q_out[sb.key] = jax.lax.with_sharding_constraint(qc, dec)
                    d_by_key[sb.key] = d_
                    if not self._prediv:
                        dc = d_.astype(cfg.inv_dtype)
                        if hc is not None:
                            dc = jnp.where(
                                ok_slots[sb.key][:, None], dc, prev_d[sb.key]
                            )
                        d_out[sb.key] = jax.lax.with_sharding_constraint(
                            dc, dec
                        )

            side(self.a_store, state.a, state.qa, state.da, qa, da,
                 d_a_by_key, ok_a_slots)
            side(self.g_store, state.g, state.qg, state.dg, qg, dg,
                 d_g_by_key, ok_g_slots)
            if self._prediv:
                # colocate-only (enforced in __post_init__): side keys are
                # the pair-bucket keys, so eigenvalue stacks align by slot
                for b in self.buckets:
                    fused = jax.vmap(
                        lambda da_, dg_, dm: factors_lib.prediv_eigenvalues(
                            factors_lib.EigenDecomp(q=None, d=da_),
                            factors_lib.EigenDecomp(q=None, d=dg_),
                            dm,
                        )
                    )(
                        d_a_by_key[b.key], d_g_by_key[b.key],
                        jnp.broadcast_to(
                            jnp.asarray(
                                slot_damping(b.layers, b.padded), jnp.float32
                            ),
                            (b.padded,),
                        ),
                    )
                    fc = fused.astype(cfg.inv_dtype)
                    if hc is not None:
                        okv = jnp.isfinite(fused).all(axis=(-2, -1))
                        ok_fused[b.key] = okv
                        fc = jnp.where(
                            okv[:, None, None], fc, state.dgda[b.key]
                        )
                    dgda[b.key] = jax.lax.with_sharding_constraint(fc, dec)
            state = state._replace(
                qa=qa, qg=qg, da=da, dg=dg, dgda=dgda,
                inv_damping=jnp.asarray(damping, jnp.float32),
            )
        else:
            a_inv, g_inv = {}, {}

            def side(store, side_state, prev, out, ok_slots):
                for sb in store:
                    cand = self._sharded_inv(
                        side_state[sb.key],
                        slot_damping(sb.layers, sb.padded),
                        prev=prev[sb.key],
                    ).astype(cfg.inv_dtype)
                    if hc is not None:
                        okv = jnp.isfinite(cand).all(axis=(-2, -1))
                        ok_slots[sb.key] = okv
                        cand = jnp.where(
                            okv[:, None, None], cand, prev[sb.key]
                        )
                    out[sb.key] = jax.lax.with_sharding_constraint(cand, dec)

            side(self.a_store, state.a, state.a_inv, a_inv, ok_a_slots)
            side(self.g_store, state.g, state.g_inv, g_inv, ok_g_slots)
            state = state._replace(
                a_inv=a_inv, g_inv=g_inv,
                inv_damping=jnp.asarray(damping, jnp.float32),
            )
        ok_layer: dict[str, jax.Array] = {}
        if hc is not None:
            # degradation counter: a refresh is quarantined when it ran
            # from a quarantined (rolled-back) factor or produced a
            # non-finite output on either side
            bad_inv = {}
            for n in self.registry.layers:
                ak, ai = self._a_slot[n]
                gk, gi = self._g_slot[n]
                okn = ok_a_slots[ak][ai] & ok_g_slots[gk][gi]
                if self._prediv:
                    okn = okn & ok_fused[ak][ai]
                ok_layer[n] = okn
                bad_inv[n] = health_lib.inversion_update(
                    hc, okn, h.quarantined[n], h.bad_inv[n]
                )
            state = state._replace(health=h._replace(bad_inv=bad_inv))
        if cfg.metrics is not None and state.metrics is not None:
            ms = state.metrics
            touched = {n: ok_layer.get(n) for n in self.registry.layers}
            state = state._replace(metrics=ms._replace(
                last_inv_step=metrics_lib.advance_last(
                    ms.last_inv_step, ms.names, touched, state.step)))
        return state

    def inverse_residuals(
        self, state: DistKFACState
    ) -> dict[str, dict[str, jax.Array]]:
        """Per-slot relative identity residuals of the CURRENT damped
        inverses: ``||I - (F + damping*I) F_inv||_F / sqrt(d)``.

        Out-of-band quality monitoring for the stacked INVERSE engine:
        the vmapped ``'newton_schulz'`` solve keeps no per-slot
        ``NewtonSchulzInfo`` in its output, so callers sample this
        between steps (e.g. each ``inv_update_steps``) and alert on
        values above :data:`kfac_tpu.ops.factors.NS_FALLBACK_RESIDUAL`.
        (``'auto'`` already self-corrects in-band: its single scalar
        runtime cond — ``factors.batched_damped_inverse_auto`` — swaps
        failed slots to the Cholesky inverse at build time.)
        Identity-padded slots report ~0. Returns
        ``{'a': {bucket_key: (L,)}, 'g': {...}}``; jit-friendly.
        """
        if self._eigen:
            raise ValueError(
                'inverse_residuals applies to the INVERSE compute method; '
                'the EIGEN path reconstructs from eigendecompositions '
                'whose quality is a property of eigh, not an iteration'
            )
        # the damping the resident inverses were BUILT with — a scheduled
        # damping resolved at the current step would add a spurious
        # |delta_damping| * ||F_inv|| floor to a perfect inverse
        damping = state.inv_damping

        def residuals(f, finv):
            d = f.shape[-1]
            eye = jnp.eye(d, dtype=jnp.float32)
            m = f.astype(jnp.float32) + damping * eye
            r = eye - jnp.einsum(
                'lij,ljk->lik', m, finv.astype(jnp.float32)
            )
            return jnp.sqrt(jnp.sum(r * r, axis=(-2, -1)) / d)

        return {
            'a': {
                sb.key: residuals(state.a[sb.key], state.a_inv[sb.key])
                for sb in self.a_store
            },
            'g': {
                sb.key: residuals(state.g[sb.key], state.g_inv[sb.key])
                for sb in self.g_store
            },
        }

    # --------------------------------------------------------- precondition

    @tracing.scope('dist_kfac.precondition')
    def precondition(
        self,
        state: DistKFACState,
        grads: Any,
        metrics_out: dict[str, jax.Array] | None = None,
    ) -> Any:
        """Precondition a params-shaped grad pytree via batched stacked math.

        Gradient stacks are laid out like the decompositions, so each column
        preconditions only its layers (its devices are the layer's "grad
        workers"); the final replication constraint is the KAISA gradient
        broadcast (reference kfac/layers/base.py:224-252).

        ``metrics_out``, when given, collects this phase's telemetry
        scalars at the replicated per-layer true-dim level (the same
        place degradation/KL run — stack-level reductions would hit the
        GSPMD partial-sum hazard described below); ``step`` merges them
        into ``state.metrics``.
        """
        cfg = self.config
        damping = _resolve(cfg.damping, state.step)
        lr = _resolve(cfg.lr, state.step)
        dec = NamedSharding(self.mesh, self._decomp_spec())
        rep = NamedSharding(self.mesh, P())
        layer_grads = registry_lib.slice_layer_grads(grads, self.registry)

        pmats: dict[str, jax.Array] = {}
        vg = jnp.zeros((), jnp.float32)
        for b in self.buckets:
            # pin each matrix to replicated before inserting: TP/SP leaves
            # per-layer grads model-sharded, and mixed shardings force
            # XLA's involuntary full rematerialization of the stack (same
            # pattern as _stack_stats). Built by dynamic-update-slice into
            # a zeros buffer rather than concatenate: GSPMD mispartitions
            # the concat-of-broadcasts under the slot-sharded constraint
            # on fractional grad-worker meshes, resolving the unused row
            # axis as partial-sum and inflating the stack by the
            # grad-worker count.
            gstack = jnp.zeros((b.padded, b.dg, b.da), cfg.inv_dtype)
            for i, n in enumerate(b.layers):
                gm = jax.lax.with_sharding_constraint(
                    self.registry.layers[n].grads_to_matrix(layer_grads[n]),
                    rep,
                )
                gstack = gstack.at[i].set(
                    pad_grad(gm, b.dg, b.da).astype(cfg.inv_dtype)
                )
            gstack = jax.lax.with_sharding_constraint(gstack, dec)

            def asm(side_dict, slot_map, row_shape):
                """Assemble this pair bucket's decomp stack from side slots.

                Colocated: side keys are pair keys and slots align — use the
                resident stack as-is (no extra collective). Non-colocated:
                gather each layer's row from its side stack and replicate
                the assembly — the decomposition exchange non-colocation
                buys its eigh parallelism with (the reference ships inverses
                to grad workers the same way, kfac/assignment.py:268-304).
                """
                if self.colocate:
                    return side_dict[b.key]
                rws = [
                    jax.lax.with_sharding_constraint(
                        side_dict[slot_map[n][0]][slot_map[n][1]], rep
                    )
                    for n in b.layers
                ]
                pad_n = b.padded - len(b.layers)
                if pad_n:
                    rws += [jnp.zeros(row_shape, rws[0].dtype)] * pad_n
                return jax.lax.with_sharding_constraint(jnp.stack(rws), rep)

            if self._prediv:
                def prec_fused(gm, qa_, qg_, fused_):
                    v1 = qg_.T @ gm @ qa_
                    return qg_ @ (v1 * fused_) @ qa_.T

                pstack = jax.vmap(prec_fused)(
                    gstack, state.qa[b.key], state.qg[b.key],
                    state.dgda[b.key],
                )
            elif self._eigen:
                qa = asm(state.qa, self._a_slot, (b.da, b.da))
                qg = asm(state.qg, self._g_slot, (b.dg, b.dg))
                dada = asm(state.da, self._a_slot, (b.da,))
                dgdg = asm(state.dg, self._g_slot, (b.dg,))
                # per-slot escalated damping bites here for the non-prediv
                # EIGEN method (its damping enters at precondition time);
                # prediv/INVERSE bake it into update_inverses
                if cfg.health is not None:
                    dmp = damping * self._slot_mults(
                        state.health, b.layers, b.padded
                    )
                else:
                    dmp = jnp.broadcast_to(
                        jnp.asarray(damping, jnp.float32), (b.padded,)
                    )

                def prec(gm, qa_, qg_, da_, dg_, dm):
                    v1 = qg_.T @ gm @ qa_
                    v2 = v1 / (jnp.outer(dg_, da_) + dm)
                    return qg_ @ v2 @ qa_.T

                pstack = jax.vmap(prec)(gstack, qa, qg, dada, dgdg, dmp)
            else:
                pstack = jax.vmap(lambda gm, ai, gi: gi @ gm @ ai)(
                    gstack,
                    asm(state.a_inv, self._a_slot, (b.da, b.da)),
                    asm(state.g_inv, self._g_slot, (b.dg, b.dg)),
                )
            pmats[b.key] = pstack

        # Extraction, graceful degradation, and KL clipping all happen on
        # replicated per-layer true-dim matrices — NOT at stack level.
        # Mixing gstack into outputs or reductions at stack level flips its
        # row-axis replication to partial-sum under GSPMD at fractional
        # grad-worker meshes and inflates values by the grad-worker count;
        # the per-layer form also matches the dense engine's vg semantics
        # exactly (kfac_tpu/preconditioner.py:precondition).
        mcfg = cfg.metrics if metrics_out is not None else None
        mats: dict[str, jax.Array] = {}
        for b in self.buckets:
            # KAISA gradient broadcast: replicate the preconditioned stack.
            pstack = jax.lax.with_sharding_constraint(pmats[b.key], rep)
            for i, name in enumerate(b.layers):
                helper = self.registry.layers[name]
                dag, dgg = b.dims[i]
                pmat = pstack[i][:dgg, :dag]
                gmat = helper.grads_to_matrix(layer_grads[name])
                if mcfg is not None:
                    if mcfg.grad_norms:
                        g32 = gmat.astype(jnp.float32)
                        metrics_out[f'grad_norm/{name}'] = jnp.sqrt(
                            jnp.sum(g32 * g32))
                    eff = (
                        damping * state.health.damping_mult[name]
                        if cfg.health is not None else damping
                    )
                    metrics_out[f'damping_eff/{name}'] = jnp.asarray(
                        eff, jnp.float32)
                if cfg.health is not None:
                    # graceful degradation: a layer past degrade_after
                    # consecutive quarantined inversions bypasses its
                    # preconditioner — the raw gradient flows through
                    # (still KL-clipped with the rest), first-order per
                    # layer
                    pmat = jnp.where(
                        health_lib.is_degraded(
                            cfg.health, state.health.bad_inv[name]
                        ),
                        gmat.astype(pmat.dtype),
                        pmat,
                    )
                if mcfg is not None and mcfg.grad_norms:
                    # pre-scale norm, next to the kl_clip reduction's read
                    # of pmat (one fused pass); rescaled by kl_clip_scale
                    # below instead of re-reading the scaled tensor
                    p32 = pmat.astype(jnp.float32)
                    metrics_out[f'precond_grad_norm/{name}'] = jnp.sqrt(
                        jnp.sum(p32 * p32))
                if cfg.kl_clip is not None:
                    vg = vg + factors_lib.kl_clip_terms(pmat, gmat, lr)
                mats[name] = pmat

        if cfg.kl_clip is not None:
            kl_clip = _resolve(cfg.kl_clip, state.step)
            scale = factors_lib.kl_clip_scale(vg, kl_clip)
        else:
            scale = None
        if mcfg is not None:
            metrics_out['kl_clip_scale'] = (
                scale.astype(jnp.float32) if scale is not None
                else jnp.ones((), jnp.float32)
            )

        out: dict[str, dict[str, jax.Array]] = {}
        for name, pmat in mats.items():
            helper = self.registry.layers[name]
            ref_dtype = layer_grads[name][next(iter(layer_grads[name]))].dtype
            if scale is not None:
                pmat = factors_lib.kl_clip_apply(pmat, scale)
                if mcfg is not None and mcfg.grad_norms:
                    metrics_out[f'precond_grad_norm/{name}'] = (
                        metrics_out[f'precond_grad_norm/{name}']
                        * jnp.abs(scale.astype(jnp.float32)))
            out[name] = helper.matrix_to_grads(pmat.astype(ref_dtype))
        return registry_lib.merge_layer_grads(grads, out, self.registry)

    # ------------------------------------------------------------------ step

    @tracing.scope('dist_kfac.step')
    def step(
        self,
        state: DistKFACState,
        grads: Any,
        stats: capture_lib.CapturedStats | None,
        loss: jax.Array | None = None,
    ) -> tuple[DistKFACState, Any]:
        """One KAISA step (same pipeline as the dense engine,
        kfac_tpu/preconditioner.py:step). ``loss``, when given, rides
        into the flight-recorder ring next to this step's scalars."""
        cfg = self.config
        # Spilled interior step (cold-factor offload): the factor stacks
        # are zero-size host-offload placeholders, statically detectable
        # at trace time. The offload pump guarantees residency on every
        # cadence boundary, so skipping the factor/inverse branches here
        # is exact — they would be no-op cond arms anyway — and keeps the
        # placeholders out of the traced branches.
        spilled = offload_lib.is_spilled(state)
        if stats is not None and not spilled:
            state = jax.lax.cond(
                state.step % _resolve(cfg.factor_update_steps, state.step) == 0,
                lambda s: self.update_factors(s, stats),
                lambda s: s,
                state,
            )
        if spilled:
            pass
        elif self._async_mode == 'sliced':
            state = async_sliced.kaisa_async_step(self, state)
        elif self._async_mode == 'host':
            state = async_host.kaisa_host_step(self, state)
        else:
            state = jax.lax.cond(
                state.step % _resolve(cfg.inv_update_steps, state.step) == 0,
                self.update_inverses,
                lambda s: s,
                state,
            )
        if cfg.metrics is not None and state.metrics is not None:
            scal: dict[str, jax.Array] = {}
            new_grads = self.precondition(state, grads, metrics_out=scal)
            ms = metrics_lib.update_scalars(state.metrics, scal)
            state = state._replace(
                metrics=metrics_lib.finalize(ms, cfg.metrics, state.step)
            )
        else:
            new_grads = self.precondition(state, grads)
        if cfg.flight is not None and state.flight is not None:
            # same placement as the dense engine: after finalize, so the
            # ring row equals what a collector drain would read this step
            state = state._replace(flight=flight_lib.record(
                state.flight,
                state.step,
                state.metrics.scalars,
                loss=loss,
                grad_norm=flight_lib.global_grad_norm(grads),
            ))
        state = state._replace(step=state.step + 1)
        return state, new_grads

    def rematerialize(self, state: DistKFACState) -> DistKFACState:
        """Recompute decompositions from factors after a checkpoint restore
        (reference semantics: kfac/base_preconditioner.py:296-308).

        Under async refresh the shadow is reset (host mode: in-flight
        worker output discarded) — the first boundary after a mid-window
        restore skips the swap, the next window refreshes normally.
        """
        if self._offload_manager is not None:
            # restored states are resident by construction — drop any
            # stale host copies/prefetches from before the restore
            self._offload_manager.reset()
        state = self.update_inverses(state)
        if self._async_mode == 'sliced':
            state = state._replace(
                shadow=async_sliced.kaisa_shadow(self, state)
            )
        elif self._async_mode == 'host':
            async_host.reset_worker(self)
        return state

    def extract_factors(
        self, state: DistKFACState
    ) -> dict[str, dict[str, jax.Array]]:
        """Per-layer true-dim factors from the stacked state.

        A topology-independent view: bucket keys, size classes, slot
        padding, and colocation are all layout choices of THIS engine
        config — the layer-named (d, d) factors are the portable content
        (the reference's per-layer factor-dir checkpoints,
        kfac/gpt_neox/preconditioner.py:394-447).
        """
        out: dict[str, dict[str, jax.Array]] = {}
        for sb in self.a_store:
            for i, name in enumerate(sb.layers):
                d = sb.dims[i]
                out.setdefault(name, {})['a'] = state.a[sb.key][i, :d, :d]
        for sb in self.g_store:
            for i, name in enumerate(sb.layers):
                d = sb.dims[i]
                out.setdefault(name, {})['g'] = state.g[sb.key][i, :d, :d]
        return out

    def insert_factors(
        self,
        state: DistKFACState,
        factors: dict[str, dict[str, jax.Array]],
    ) -> DistKFACState:
        """Write per-layer factors into this engine's stacked layout
        (inverse of :meth:`extract_factors`; layers absent from
        ``factors`` keep their current rows). Call
        :meth:`rematerialize` afterwards to rebuild decompositions."""

        def rewrite(store, side):
            out = {}
            for sb in store:
                stack = (
                    state.a[sb.key] if side == 'a' else state.g[sb.key]
                )
                idxs = [
                    i for i, n in enumerate(sb.layers) if n in factors
                ]
                if idxs:
                    # one scatter per bucket, not one full-stack copy per
                    # layer
                    rows = jnp.stack([
                        pad_factor(
                            factors[sb.layers[i]][side].astype(
                                self.config.factor_dtype
                            ),
                            sb.d,
                        )
                        for i in idxs
                    ])
                    stack = stack.at[jnp.asarray(idxs)].set(rows)
                out[sb.key] = stack
            return out

        return state._replace(
            a=rewrite(self.a_store, 'a'), g=rewrite(self.g_store, 'g')
        )

    def describe(self) -> str:
        """Registration + placement dump: the reference's construction-time
        assignment logging (kfac/preconditioner.py:264-268,300) as a
        pull-based table — strategy, bucket layout, and per-layer inverse
        workers from the KAISA assignment."""
        lines = [
            f'DistributedKFAC: {len(self.registry.layers)} layers over '
            f'{self.total_devices} devices '
            f'(grid {self.grad_workers}x{mesh_lib.n_cols(self.mesh)}), '
            f'strategy={self.strategy.name}, colocate={self.colocate}, '
            f'method={self.config.compute_method.name}',
            self.config.describe(),
            'stat transport buckets (stacked batched decompositions):',
        ]
        for b in self.buckets:
            lines.append(
                f'  bucket da={b.da} dg={b.dg}: '
                f'{len(b.layers)} layers, {b.padded} padded slots'
            )
        lines.append(
            'factor storage fill (resident vs padding bytes per size '
            'class):'
        )
        for key, p in comms_lib.padding_report(self).items():
            lines.append(
                f'  {key}: {p["layers"]} layers in {p["slots"]} slots, '
                f'resident {p["resident_bytes"]} B, '
                f'identity-pad {p["identity_pad_bytes"]} B, '
                f'slot-pad {p["slot_pad_bytes"]} B, '
                f'fill {p["fill"]:.0%}'
            )
        lines.append(
            'executed placement (slot round-robin within stacked buckets; '
            'decomposition runs where the slot lives):'
        )
        for name in self.registry.names():
            a_key, a_i = self._a_slot[name]
            g_key, g_i = self._g_slot[name]
            a_dev = self.slot_device('a', name)
            g_dev = self.slot_device('g', name)
            lines.append(
                f'  {name}: A slot {a_key}[{a_i}] -> device {a_dev.id}, '
                f'G slot {g_key}[{g_i}] -> device {g_dev.id}'
            )
        lines.append(
            'inverse workers, cost-model view (KAISA greedy assignment — '
            'reference-parity diagnostic, NOT the executed placement above):'
        )
        for layer in self.assignment.get_layers():
            workers = {
                f: self.assignment.inv_worker(layer, f)
                for f in self.assignment.get_factors(layer)
            }
            lines.append(f'  {layer}: {workers}')
        return '\n'.join(lines)

    def topology(self) -> dict[str, Any]:
        """Process/device/mesh topology snapshot, recorded
        (informationally) into checkpoint layout manifests so an elastic
        restore can report which topologies it moved a checkpoint
        between."""
        import numpy as _np

        return {
            'process_count': jax.process_count(),
            'device_count': jax.device_count(),
            'backend': jax.default_backend(),
            'mesh_axes': list(self.mesh.axis_names),
            'mesh_shape': [int(s) for s in _np.shape(self.mesh.devices)],
        }

    def slot_device(self, side: str, name: str) -> Any:
        """The device that stores AND decomposes ``name``'s A or G factor.

        Factor stacks shard their leading slot axis over every mesh axis
        (``_factor_spec``), so mesh-linear device ``j`` owns slots
        ``[j*spd, (j+1)*spd)`` with ``spd = padded / total_devices`` —
        the executed counterpart of the reference's per-rank inv_worker
        query (kfac/assignment.py), asserted against the real shard layout
        in tests.
        """
        slot_map = self._a_slot if side == 'a' else self._g_slot
        store = self.a_store if side == 'a' else self.g_store
        key, i = slot_map[name]
        padded = next(sb.padded for sb in store if sb.key == key)
        spd = padded // self.total_devices
        import numpy as _np

        return _np.asarray(self.mesh.devices).reshape(-1)[i // spd]

    def comms_report(self) -> dict[str, Any]:
        """Host-side comms/padding byte accounting for this configuration.

        See :func:`kfac_tpu.observability.comms.comms_summary`: stat
        transport bytes and chunk plan, inverse-reshard and
        gradient-broadcast payloads, and per-size-class padding waste —
        the measurable side of the KAISA gradient-worker-fraction trade.
        """
        out = comms_lib.comms_summary(self)
        if self._offload_manager is not None and 'offload' in out:
            # static plan (comms_summary) + live transfer/hit counters
            out['offload'] = dict(
                out['offload'], **self._offload_manager.stats
            )
        return out

    def compile_watcher(
        self,
    ) -> 'compile_watch_lib.CompileWatch | None':
        """This engine's :class:`~kfac_tpu.observability.compile_watch.
        CompileWatch`, built lazily from ``config.compile_watch`` (None
        when disabled). The Trainer's step paths count into the same
        watch, so one report covers the whole program surface."""
        if self.config.compile_watch is None:
            return None
        watch = getattr(self, '_compile_watcher', None)
        if watch is None:
            watch = compile_watch_lib.CompileWatch(self.config.compile_watch)
            self._compile_watcher = watch
        return watch

    def watched(self, entry: str) -> Any:
        """A jitted, watch-wrapped IR entry point (``'step'``,
        ``'update_factors'``, ...). Requires ``config.compile_watch``."""
        if entry not in self.IR_ENTRY_POINTS:
            raise ValueError(
                f'unknown entry {entry!r}; expected one of '
                f'{self.IR_ENTRY_POINTS}'
            )
        watch = self.compile_watcher()
        if watch is None:
            raise ValueError(
                'watched() requires compile_watch enabled on config'
            )
        cache = getattr(self, '_watched_entries', None)
        if cache is None:
            cache = {}
            self._watched_entries = cache
        if entry not in cache:
            cache[entry] = watch.wrap(
                f'dist_kfac.{entry}', jax.jit(getattr(self, entry))
            )
        return cache[entry]

    def compiled_memory_report(self) -> dict[str, dict[str, Any]]:
        """Latest XLA ``memory_analysis()`` snapshot per watched entry —
        the measured counterpart of :meth:`memory_usage` (which estimates
        from shard shapes) and the number autotune's
        ``HardwareSpec.hbm_bytes`` pruning should be checked against.
        Empty when the watch is off or the backend doesn't report."""
        watch = self.compile_watcher()
        return {} if watch is None else watch.memory_report()

    def memory_usage(self, state: DistKFACState) -> dict[str, Any]:
        """Per-device bytes by category, read from the ACTUAL shard layout.

        Each array's per-device footprint is its sharding's shard shape —
        the truth for asymmetric/real layouts — rather than fraction
        arithmetic from the strategy (VERDICT round 1: estimates mislead on
        asymmetric layouts). Falls back to strategy fractions only for
        abstract values (e.g. under trace).

        ``total`` sums the four factor/inverse categories;
        ``padding_waste`` (nested, GLOBAL logical bytes — not per-device)
        breaks resident factor bytes out of the size-class padding, per
        storage bucket plus totals, so the cost of bucket granularity is
        visible next to the resident footprint.

        On a SPILLED state (cold-factor offload interior step) the
        ``a_factors``/``g_factors`` categories read ~0 bytes — the
        placeholders' true footprint — which is exactly the HBM relief
        the offload buys; ``comms_report()['offload']`` carries the
        host-resident byte count.
        """
        shard_f = 1.0 / self.total_devices
        if self.strategy == enums.DistributedStrategy.COMM_OPT:
            shard_d = 1.0
        else:
            shard_d = 1.0 / mesh_lib.n_cols(self.mesh)

        def per_device(v: jax.Array, frac: float) -> int:
            sharding = getattr(v, 'sharding', None)
            if sharding is not None and hasattr(sharding, 'shard_shape'):
                try:
                    shape = sharding.shard_shape(v.shape)
                except Exception:  # abstract/manual values
                    return int(v.size * v.dtype.itemsize * frac)
                n = 1
                for s in shape:
                    n *= int(s)
                return n * v.dtype.itemsize
            return int(v.size * v.dtype.itemsize * frac)

        def nbytes(d: dict[str, jax.Array], frac: float) -> int:
            return int(sum(per_device(v, frac) for v in d.values()))

        sizes = {
            'a_factors': nbytes(state.a, shard_f),
            'g_factors': nbytes(state.g, shard_f),
            'a_inverses': nbytes(state.qa, shard_d) + nbytes(state.da, shard_d)
            + nbytes(state.a_inv, shard_d),
            'g_inverses': nbytes(state.qg, shard_d) + nbytes(state.dg, shard_d)
            + nbytes(state.dgda, shard_d) + nbytes(state.g_inv, shard_d),
        }
        sizes['total'] = sum(sizes.values())
        padding = comms_lib.padding_report(self)
        sizes['padding_waste'] = {
            'per_class': padding,
            'resident_bytes': sum(
                p['resident_bytes'] for p in padding.values()),
            'identity_pad_bytes': sum(
                p['identity_pad_bytes'] for p in padding.values()),
            'slot_pad_bytes': sum(
                p['slot_pad_bytes'] for p in padding.values()),
        }
        return sizes
