"""Tensor-parallel parameter sharding rules (Megatron-style).

Capability parity with the reference's GPT-NeoX subpackage
(kfac/gpt_neox/: ColumnParallelLinear/RowParallelLinear recognition,
gather-precondition-rescatter of sharded layers, TP-aware factor shapes).
Under pjit the machinery dissolves into *layout rules*:

- Column-parallel (output-sharded) and row-parallel (input-sharded) weights
  are just PartitionSpecs over the ``model`` axis; activations between the
  paired projections stay sharded over ``model`` and XLA inserts the same
  all-reduce Megatron does by hand.
- K-FAC factor statistics are computed from *global* activations/cotangents
  (the interceptor sees global arrays), so the reference's primary-rank
  gather of sharded activations (kfac/gpt_neox/layer.py:129-163) becomes an
  XLA-chosen collective in the covariance contraction.
- Preconditioning a sharded weight gathers its gradient into the stacked
  bucket, preconditions, and reshards on write-back — semantically the
  reference's gather -> precondition -> scatter (kfac/gpt_neox/layer.py:
  165-311), scheduled by the compiler.

Rules are regex -> PartitionSpec over flattened param paths, in the spirit
of flax's logical partitioning but without requiring model changes.
"""

from __future__ import annotations

import re
import warnings as _warnings
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kfac_tpu.layers import helpers as helpers_lib
from kfac_tpu.parallel import mesh as mesh_lib
from kfac_tpu.warnings import ExperimentalFeatureWarning

# (path regex, spec) — first match wins; default replicated.
TRANSFORMER_TP_RULES: tuple[tuple[str, P], ...] = (
    # column-parallel: shard output features
    (r'.*(q_proj|k_proj|v_proj|mlp_up)/kernel', P(None, mesh_lib.MODEL_AXIS)),
    (r'.*(q_proj|k_proj|v_proj|mlp_up)/bias', P(mesh_lib.MODEL_AXIS)),
    # row-parallel: shard input features; bias replicated
    (r'.*(out_proj|mlp_down)/kernel', P(mesh_lib.MODEL_AXIS, None)),
    # output head: vocab-sharded
    (r'.*lm_head/kernel', P(None, mesh_lib.MODEL_AXIS)),
)


def param_specs(
    params: Any,
    rules: Sequence[tuple[str, P]] = TRANSFORMER_TP_RULES,
) -> Any:
    """PartitionSpec pytree for ``params`` from path-regex rules."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(path, leaf) -> P:
        name = '/'.join(str(getattr(k, 'key', k)) for k in path)
        for pat, spec in compiled:
            if pat.fullmatch(name):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_params(
    params: Any,
    mesh: Mesh,
    rules: Sequence[tuple[str, P]] = TRANSFORMER_TP_RULES,
) -> Any:
    """Place ``params`` on the mesh according to the TP rules."""
    specs = param_specs(params, rules)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


class UnshardedParamWarning(ExperimentalFeatureWarning):
    """A parameter matched no TP rule and stays replicated."""


def _layer_specs(helper, kind: str, axis: str) -> dict[str, P]:
    """kernel/bias PartitionSpecs for one layer given its parallel kind.

    flax layouts: Dense kernel (in, out); Conv kernel (kh, kw, in, out).
    column-parallel shards the output features (bias sharded with them);
    row-parallel shards the input features (bias replicated, since outputs
    are partial sums that all-reduce before the bias add) — the reference's
    ColumnParallelLinear / RowParallelLinear layouts (kfac/gpt_neox/).
    """
    is_conv = isinstance(helper, helpers_lib.Conv2dHelper)
    if kind == 'column':
        kernel = (
            P(None, None, None, axis) if is_conv else P(None, axis)
        )
        return {'kernel': kernel, 'bias': P(axis)}
    if kind == 'row':
        kernel = (
            P(None, None, axis, None) if is_conv else P(axis, None)
        )
        return {'kernel': kernel, 'bias': P()}
    return {'kernel': P(), 'bias': P()}


def derive_layer_kinds(
    registry: Any,
    overrides: Sequence[tuple[str, str]] | None = None,
) -> dict[str, str]:
    """Per-registered-layer parallel kind: 'column', 'row', or 'replicated'.

    ``overrides`` are (layer-name regex, kind) pairs — the user-declaration
    analogue of the reference's ColumnParallelLinear/RowParallelLinear
    module types (kfac/gpt_neox/). Layers matched by no override get the
    shard-the-wide-side default: expanding layers (out > in) are
    column-parallel, contracting layers (out < in) row-parallel — the
    Megatron MLP pairing — and square layers stay replicated (sharding them
    needs a declaration of which side their neighbours shard).
    """
    compiled = [(re.compile(pat), kind) for pat, kind in (overrides or [])]
    for _, kind in compiled:
        if kind not in ('column', 'row', 'replicated'):
            raise ValueError(f'unknown parallel kind {kind!r}')
    kinds: dict[str, str] = {}
    for name, helper in registry.layers.items():
        kind = None
        for pat, k in compiled:
            if pat.fullmatch(name):
                kind = k
                break
        if kind is None:
            d_out = helper.g_factor_shape[0]
            d_in = helper.a_factor_shape[0] - int(helper.has_bias)
            kind = (
                'column' if d_out > d_in
                else 'row' if d_out < d_in
                else 'replicated'
            )
        kinds[name] = kind
    return kinds


def registry_param_specs(
    params: Any,
    registry: Any,
    overrides: Sequence[tuple[str, str]] | None = None,
    axis: str = mesh_lib.MODEL_AXIS,
    warn_unmatched: bool = True,
) -> Any:
    """PartitionSpec pytree derived from the K-FAC registry.

    Works on any registered model (no dependence on this repo's layer
    names). Parameters belonging to no registered layer (embeddings, norms,
    skipped layers) stay replicated; with ``warn_unmatched`` a warning lists
    them once so silent full replication of a model the user meant to shard
    is visible (VERDICT round 1: the regex table silently replicated
    unknown models).
    """
    kinds = derive_layer_kinds(registry, overrides)
    spec_by_path: dict[tuple[str, ...], dict[str, P]] = {
        registry.param_paths[name]: _layer_specs(
            registry.layers[name], kind, axis
        )
        for name, kind in kinds.items()
    }

    unmatched: list[str] = []

    def spec_for(path, leaf) -> P:
        keys = tuple(str(getattr(k, 'key', k)) for k in path)
        layer_spec = spec_by_path.get(keys[:-1])
        if layer_spec is not None and keys[-1] in layer_spec:
            return layer_spec[keys[-1]]
        unmatched.append('/'.join(keys))
        return P()

    specs = jax.tree_util.tree_map_with_path(spec_for, params)
    if warn_unmatched and unmatched:
        shown = ', '.join(unmatched[:5])
        more = f' (+{len(unmatched) - 5} more)' if len(unmatched) > 5 else ''
        _warnings.warn(
            f'{len(unmatched)} params matched no TP rule and stay '
            f'replicated: {shown}{more}',
            UnshardedParamWarning,
            stacklevel=2,
        )
    return specs


def shard_params_from_registry(
    params: Any,
    mesh: Mesh,
    registry: Any,
    overrides: Sequence[tuple[str, str]] | None = None,
    axis: str = mesh_lib.MODEL_AXIS,
    warn_unmatched: bool = True,
) -> Any:
    """Shard ``params`` using registry-derived TP rules (any model)."""
    specs = registry_param_specs(
        params, registry, overrides, axis, warn_unmatched
    )
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )
