"""Tensor-parallel parameter sharding rules (Megatron-style).

Capability parity with the reference's GPT-NeoX subpackage
(kfac/gpt_neox/: ColumnParallelLinear/RowParallelLinear recognition,
gather-precondition-rescatter of sharded layers, TP-aware factor shapes).
Under pjit the machinery dissolves into *layout rules*:

- Column-parallel (output-sharded) and row-parallel (input-sharded) weights
  are just PartitionSpecs over the ``model`` axis; activations between the
  paired projections stay sharded over ``model`` and XLA inserts the same
  all-reduce Megatron does by hand.
- K-FAC factor statistics are computed from *global* activations/cotangents
  (the interceptor sees global arrays), so the reference's primary-rank
  gather of sharded activations (kfac/gpt_neox/layer.py:129-163) becomes an
  XLA-chosen collective in the covariance contraction.
- Preconditioning a sharded weight gathers its gradient into the stacked
  bucket, preconditions, and reshards on write-back — semantically the
  reference's gather -> precondition -> scatter (kfac/gpt_neox/layer.py:
  165-311), scheduled by the compiler.

Rules are regex -> PartitionSpec over flattened param paths, in the spirit
of flax's logical partitioning but without requiring model changes.
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kfac_tpu.parallel import mesh as mesh_lib

# (path regex, spec) — first match wins; default replicated.
TRANSFORMER_TP_RULES: tuple[tuple[str, P], ...] = (
    # column-parallel: shard output features
    (r'.*(q_proj|k_proj|v_proj|mlp_up)/kernel', P(None, mesh_lib.MODEL_AXIS)),
    (r'.*(q_proj|k_proj|v_proj|mlp_up)/bias', P(mesh_lib.MODEL_AXIS)),
    # row-parallel: shard input features; bias replicated
    (r'.*(out_proj|mlp_down)/kernel', P(mesh_lib.MODEL_AXIS, None)),
    # output head: vocab-sharded
    (r'.*lm_head/kernel', P(None, mesh_lib.MODEL_AXIS)),
)


def param_specs(
    params: Any,
    rules: Sequence[tuple[str, P]] = TRANSFORMER_TP_RULES,
) -> Any:
    """PartitionSpec pytree for ``params`` from path-regex rules."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(path, leaf) -> P:
        name = '/'.join(str(getattr(k, 'key', k)) for k in path)
        for pat, spec in compiled:
            if pat.fullmatch(name):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_params(
    params: Any,
    mesh: Mesh,
    rules: Sequence[tuple[str, P]] = TRANSFORMER_TP_RULES,
) -> Any:
    """Place ``params`` on the mesh according to the TP rules."""
    specs = param_specs(params, rules)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )
