"""Numerical-health sentinel: skip-step, factor quarantine, degradation.

K-FAC is the most numerically fragile part of the stack: factors are
EMA'd running covariances, inverted in fp32, and a single non-finite
capture poisons ``A``/``G`` for every subsequent step — the reference
implementation simply crashes or silently diverges in this regime
(kfac/layers/eigen.py). Large-scale training reports (OPT/PaLM-style
logs) consistently cite skip-step + escalated damping as the load-bearing
recovery mechanisms for (near-)second-order optimizers. This module makes
a transient loss spike, a pathological decomposition, or one bad
microbatch degrade a *layer*, not the *run*:

1. **Skip-step** — a cheap fused finiteness reduction over loss + grads
   gates the whole update (params, optimizer, factors) via ``lax.cond``
   inside the jitted step, incrementing :attr:`HealthState.skipped_steps`
   instead of applying a poisoned update. Wired in
   :class:`kfac_tpu.training.Trainer` (all execution paths: ``step``,
   ``scan_steps``, and the gradient-accumulation family).
2. **Factor quarantine** — per layer, a factor update whose EMA'd result
   is non-finite or whose Gershgorin condition bound exceeds
   :attr:`HealthConfig.quarantine_threshold` is rolled back to the
   previous factor, and the layer's damping multiplier escalates
   (decaying back toward 1.0 on healthy updates). Wired in both engines'
   ``update_factors``.
3. **Graceful degradation** — after :attr:`HealthConfig.degrade_after`
   consecutive quarantined inversions (the inverse refresh ran from a
   quarantined factor, or its own output was non-finite), the layer's
   preconditioner is bypassed — its update is the raw gradient direction
   — until the health counter recovers. The run continues as
   partially-first-order rather than dying. Wired in both engines'
   ``update_inverses`` / ``precondition``.

All health state lives in :class:`HealthState` as plain scalar arrays
(jit-, scan-, and checkpoint-compatible; per-layer scalars are
layout-independent, so they ride identically in the dense
:class:`~kfac_tpu.preconditioner.KFACState` and the stacked
:class:`~kfac_tpu.parallel.kaisa.DistKFACState`, under either stat
transport). Counters are surfaced host-side through
:func:`kfac_tpu.tracing.health_counters` and rate-limited warnings
through :func:`kfac_tpu.warnings.warn_health_event`.

Deterministic fault injection for all three mechanisms lives in
``testing/faults.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, NamedTuple

import jax
import jax.numpy as jnp

from kfac_tpu import warnings as kfac_warnings
from kfac_tpu.ops import factors as factors_lib


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Knobs of the numerical-health sentinel.

    Pass an instance as ``KFACPreconditioner(health=...)`` (or
    ``health=True`` for these defaults); ``health=None`` (the default)
    disables all health machinery — zero state, zero per-step cost,
    reference semantics (a non-finite capture crashes or silently
    diverges the run).

    Args:
        skip_nonfinite: gate the whole train-step update (params,
            optimizer state, factors) on a fused finiteness check of
            loss + gradients. The reference's closest analogue is the
            AMP grad-scaler skip (torch.cuda.amp); here it guards every
            precision mode.
        quarantine_threshold: a factor update whose Gershgorin condition
            bound (``ops/factors.gershgorin_condition_bound`` at the
            layer's effective damping) exceeds this is quarantined even
            when finite — its fp32 inverse could not be trusted anyway
            (forward error ``O(kappa * eps)``). ``None`` disables the
            conditioning check (finiteness-only quarantine).
        damping_escalation: per quarantine event, the layer's damping
            multiplier is multiplied by this (>1). Escalated damping is
            the standard recovery lever: it pulls the preconditioner
            toward (scaled) SGD for exactly the layer that misbehaved.
        damping_decay: on each healthy factor update the multiplier
            decays by this (in (0, 1)), floored at 1.0 — transient
            events anneal back to nominal damping.
        max_damping_mult: cap on the multiplier, bounding how far a
            persistently bad layer can escalate.
        degrade_after: consecutive quarantined inversions after which the
            layer's preconditioner is bypassed (identity — the raw
            gradient direction). Recovery is hysteretic: each healthy
            inversion decrements the counter, so a layer degraded at K
            needs healthy inversions to climb back below K.
        warn: emit rate-limited host-side warnings (via
            :func:`check_and_warn`) from the Trainer's eager paths the
            first time a layer is quarantined or degraded. Reading the
            counters synchronizes with the device, so latency-critical
            loops (or fully compiled ``scan_steps`` loops, which never
            return to the host mid-run) should leave this to an explicit
            ``Trainer.check_health`` call at their logging cadence.
    """

    skip_nonfinite: bool = True
    quarantine_threshold: float | None = 1e8
    damping_escalation: float = 10.0
    damping_decay: float = 0.5
    max_damping_mult: float = 1e6
    degrade_after: int = 3
    warn: bool = True

    def __post_init__(self) -> None:
        if self.damping_escalation <= 1.0:
            raise ValueError(
                f'damping_escalation must be > 1, got {self.damping_escalation}'
            )
        if not 0.0 < self.damping_decay < 1.0:
            raise ValueError(
                f'damping_decay must be in (0, 1), got {self.damping_decay}'
            )
        if self.max_damping_mult < self.damping_escalation:
            raise ValueError(
                'max_damping_mult must be >= damping_escalation, got '
                f'{self.max_damping_mult}'
            )
        if self.degrade_after < 1:
            raise ValueError(
                f'degrade_after must be >= 1, got {self.degrade_after}'
            )
        if (
            self.quarantine_threshold is not None
            and self.quarantine_threshold <= 1.0
        ):
            raise ValueError(
                'quarantine_threshold is a condition-number bound and must '
                f'be > 1 (or None to disable), got {self.quarantine_threshold}'
            )


class HealthState(NamedTuple):
    """Per-run + per-layer health counters, all plain scalar arrays.

    ``skipped_steps``: whole-batch updates dropped by the skip-step gate.
    ``damping_mult``: per-layer damping escalation multiplier (>= 1).
    ``quarantined``: per-layer CONSECUTIVE quarantined factor updates
    (0 = the layer's resident factor is its own latest update).
    ``bad_inv``: per-layer consecutive quarantined inversions — the
    degradation counter (clamped at ``2 * degrade_after`` so recovery
    from a long outage is bounded).
    ``quarantine_events``: per-layer CUMULATIVE quarantine events, for
    tracing/warnings (monotone; never reset).
    """

    skipped_steps: jax.Array
    damping_mult: dict[str, jax.Array]
    quarantined: dict[str, jax.Array]
    bad_inv: dict[str, jax.Array]
    quarantine_events: dict[str, jax.Array]


def init_health(names: Iterable[str]) -> HealthState:
    """Fresh (healthy) counters for the given registered layer names."""
    names = list(names)
    return HealthState(
        skipped_steps=jnp.zeros((), jnp.int32),
        damping_mult={n: jnp.ones((), jnp.float32) for n in names},
        quarantined={n: jnp.zeros((), jnp.int32) for n in names},
        bad_inv={n: jnp.zeros((), jnp.int32) for n in names},
        quarantine_events={n: jnp.zeros((), jnp.int32) for n in names},
    )


def health_metric_keys(names: Iterable[str]) -> list[str]:
    """The ``health/*`` key schema that ``tracing.health_counters`` emits.

    Matches :func:`kfac_tpu.tracing.log_health` / the collector fold-in in
    :class:`kfac_tpu.observability.metrics.MetricsCollector` key-for-key;
    documented in docs/OBSERVABILITY.md.
    """
    keys = ['health/skipped_steps']
    for n in names:
        for field in (
            'damping_mult', 'quarantined', 'bad_inv', 'quarantine_events'
        ):
            keys.append(f'health/{n}/{field}')
    return keys


# ----------------------------------------------------------------- predicates


def all_finite(*trees: Any) -> jax.Array:
    """Scalar bool: every inexact leaf of every tree is free of inf/nan.

    The skip-step sentinel: one ``isfinite().all()`` per leaf, combined
    by a single stacked reduction — XLA fuses this into the backward pass
    it already ran, so the gate costs one elementwise sweep, no extra
    host sync (contrast the reference's grad-scaler ``.item()`` check).
    """
    flags = []
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            x = jnp.asarray(leaf)
            if jnp.issubdtype(x.dtype, jnp.inexact):
                flags.append(jnp.isfinite(x).all())
    if not flags:
        return jnp.asarray(True)
    return jnp.stack(flags).all()


def factor_ok(
    candidate: jax.Array,
    damping: jax.Array | float,
    threshold: float | None,
) -> jax.Array:
    """Per-factor health verdict for a ``(..., d, d)`` stack -> ``(...,)``.

    A factor update is healthy when it is finite AND (if ``threshold``)
    its Gershgorin condition bound at the layer's effective damping stays
    below the quarantine threshold. A NaN factor yields a NaN bound whose
    comparison is False, so both legs fail closed.
    """
    ok = jnp.isfinite(candidate).all(axis=(-2, -1))
    if threshold is not None:
        bound = factors_lib.gershgorin_condition_bound(candidate, damping)
        ok = ok & (bound <= threshold)
    return ok


# ---------------------------------------------------------------- transitions
# All transition helpers broadcast: scalars for the dense per-layer engine,
# (L,) slot vectors for the stacked KAISA engine — one implementation of the
# state machine, two layouts.


def quarantine_update(
    cfg: HealthConfig,
    ok: jax.Array,
    mult: jax.Array,
    quarantined: jax.Array,
    events: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Factor-update-time transition: escalate on quarantine, decay on
    health. Returns ``(damping_mult, quarantined, quarantine_events)``."""
    bad = ~ok
    new_mult = jnp.where(
        bad,
        jnp.minimum(mult * cfg.damping_escalation, cfg.max_damping_mult),
        jnp.maximum(1.0, mult * cfg.damping_decay),
    )
    new_quarantined = jnp.where(bad, quarantined + 1, 0)
    new_events = events + bad.astype(events.dtype)
    return new_mult, new_quarantined, new_events


def inversion_update(
    cfg: HealthConfig,
    ok: jax.Array,
    quarantined: jax.Array,
    bad_inv: jax.Array,
) -> jax.Array:
    """Inversion-time transition of the degradation counter.

    An inversion is *quarantined* when it ran from a quarantined (stale,
    rolled-back) factor or its own output was non-finite. The counter is
    clamped at ``2 * degrade_after`` so a layer broken for thousands of
    steps still recovers after ``degrade_after + 1`` healthy inversions
    instead of paying back the whole outage.
    """
    bad = (~ok) | (quarantined > 0)
    cap = 2 * cfg.degrade_after
    return jnp.where(
        bad,
        jnp.minimum(bad_inv + 1, cap),
        jnp.maximum(bad_inv - 1, 0),
    )


def is_degraded(cfg: HealthConfig, bad_inv: jax.Array) -> jax.Array:
    """Bool (scalar or (L,)): the layer's preconditioner is bypassed."""
    return bad_inv >= cfg.degrade_after


def mark_skipped(state: Any) -> Any:
    """Skip-step branch: advance the step clock, count the skip, change
    NOTHING else (params/optimizer are untouched by the caller's cond).

    The step counter advances so hyperparameter schedules and the
    factor/inverse cadence stay aligned with the host-side dispatch
    mirror — the *update* is skipped, not the clock.
    """
    h = state.health
    return state._replace(
        step=state.step + 1,
        health=h._replace(skipped_steps=h.skipped_steps + 1),
    )


# ------------------------------------------------------------- host utilities


def summary(cfg: HealthConfig, health: HealthState) -> dict[str, Any]:
    """Host-side snapshot: counters + derived per-layer status strings.

    Synchronizes with the device (one small transfer). Layers are
    ``'ok'``, ``'quarantined'`` (living on a rolled-back factor), or
    ``'degraded'`` (preconditioner bypassed).
    """
    vals = jax.device_get(health._asdict())
    layers = {}
    for n in vals['damping_mult']:
        bad_inv = int(vals['bad_inv'][n])
        if bad_inv >= cfg.degrade_after:
            status = 'degraded'
        elif int(vals['quarantined'][n]) > 0:
            status = 'quarantined'
        else:
            status = 'ok'
        layers[n] = {
            'status': status,
            'damping_mult': float(vals['damping_mult'][n]),
            'quarantined': int(vals['quarantined'][n]),
            'bad_inv': bad_inv,
            'quarantine_events': int(vals['quarantine_events'][n]),
        }
    return {
        'skipped_steps': int(vals['skipped_steps']),
        'layers': layers,
    }


def check_and_warn(
    cfg: HealthConfig,
    health: HealthState,
    step: int | None = None,
) -> dict[str, Any]:
    """Scan counters and emit the rate-limited first-occurrence warnings.

    Emits one :class:`kfac_tpu.warnings.NumericalHealthWarning` per
    (layer, cause) for the life of the process — the first time a layer
    shows a quarantine event and the first time it crosses into
    degradation — instead of spamming every step (see
    ``kfac_tpu.warnings.warn_health_event``). Returns the
    :func:`summary` it scanned, so logging callers pay the device sync
    once.
    """
    snap = summary(cfg, health)
    for name, info in snap['layers'].items():
        if info['quarantine_events'] > 0:
            kfac_warnings.warn_health_event(
                name, step, 'quarantined',
                detail=(
                    f"{info['quarantine_events']} quarantine event(s), "
                    f"damping_mult={info['damping_mult']:g}"
                ),
            )
        if info['status'] == 'degraded':
            kfac_warnings.warn_health_event(
                name, step, 'degraded',
                detail=(
                    f"{info['bad_inv']} consecutive quarantined "
                    f'inversions (>= degrade_after={cfg.degrade_after}); '
                    'preconditioner bypassed, raw gradient in use'
                ),
            )
    return snap
