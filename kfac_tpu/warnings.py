"""Warning categories (reference parity: kfac/warnings.py:6-9) plus the
rate-limited numerical-health event channel (kfac_tpu/health.py)."""

from __future__ import annotations

import warnings as _warnings


class ExperimentalFeatureWarning(Warning):
    """Feature is experimental and may change or underperform."""


class TPUPerformanceWarning(Warning):
    """Configuration known to be pathologically slow on TPU backends."""


class NumericalHealthWarning(Warning):
    """A layer was quarantined or degraded by the health sentinel."""


class CheckpointResilienceWarning(Warning):
    """Checkpoint durability/restore anomaly that was handled gracefully
    (manifest-less restore, fallback to an older rotation entry, retried
    transient I/O) but an operator should know about."""


class LayoutPlanWarning(Warning):
    """A tuned layout plan (kfac_tpu/autotune) could not be applied —
    topology/model fingerprint mismatch, incompatible mesh — and the
    engine fell back to its explicit/default configuration."""


class DispatchTableWarning(Warning):
    """A Pallas dispatch gate held its conservative (XLA) default because
    the committed threshold artifact's backing sweep is latency-floor
    contaminated (kfac_tpu/ops/dispatch_tables.py) — the threshold it
    would have used never measured the op."""


class FleetWarning(Warning):
    """A self-driving fleet event (kfac_tpu/resilience/fleet.py) an
    operator should know about: a topology-change retune, a drift-
    triggered migration abort/rollback, a fallback to the canonical
    layout."""


# (layer, cause) pairs already warned about — each fires ONCE per process,
# not once per step: a persistently sick layer would otherwise spam the log
# at training-step frequency while saying nothing new.
_health_events_emitted: set[tuple[str, str]] = set()


def warn_health_event(
    layer: str,
    step: int | None,
    cause: str,
    detail: str = '',
) -> bool:
    """Emit a structured, rate-limited :class:`NumericalHealthWarning`.

    ``cause`` is a short event tag (``'quarantined'``, ``'degraded'``).
    Returns True when a warning was actually emitted (first occurrence of
    this (layer, cause)), False when rate-limited.
    """
    key = (layer, cause)
    if key in _health_events_emitted:
        return False
    _health_events_emitted.add(key)
    at = f' at step {step}' if step is not None else ''
    msg = f'kfac-tpu health: layer {layer!r} {cause}{at}'
    if detail:
        msg += f' ({detail})'
    _warnings.warn(msg, NumericalHealthWarning, stacklevel=2)
    return True


def reset_health_warnings() -> None:
    """Forget emitted health events (tests; or after operator intervention
    so a recurrence warns again)."""
    _health_events_emitted.clear()


# plan-fallback causes already warned about — once per process, like the
# health channel: a stale plan would otherwise warn on every engine (or
# Trainer) construction in a sweep while saying nothing new.
_layout_events_emitted: set[str] = set()


def warn_layout_event(cause: str, detail: str = '') -> bool:
    """Emit a rate-limited :class:`LayoutPlanWarning` (once per ``cause``).

    Returns True when a warning was actually emitted."""
    if cause in _layout_events_emitted:
        return False
    _layout_events_emitted.add(cause)
    msg = f'kfac-tpu autotune: tuned plan not applied — {cause}'
    if detail:
        msg += f' ({detail})'
    msg += '; falling back to the explicit/default layout'
    _warnings.warn(msg, LayoutPlanWarning, stacklevel=2)
    return True


def reset_layout_warnings() -> None:
    """Forget emitted plan-fallback events (tests)."""
    _layout_events_emitted.clear()


# fleet causes already warned about — once per process per cause, like
# the layout channel: the per-occurrence record lives in
# FleetController.events, the warning only flags the first one.
_fleet_events_emitted: set[str] = set()


def warn_fleet_event(cause: str, detail: str = '') -> bool:
    """Emit a rate-limited :class:`FleetWarning` (once per ``cause``).

    Returns True when a warning was actually emitted."""
    if cause in _fleet_events_emitted:
        return False
    _fleet_events_emitted.add(cause)
    msg = f'kfac-tpu fleet: {cause}'
    if detail:
        msg += f' ({detail})'
    _warnings.warn(msg, FleetWarning, stacklevel=2)
    return True


def reset_fleet_warnings() -> None:
    """Forget emitted fleet events (tests)."""
    _fleet_events_emitted.clear()


# families already warned about — once per process per family: the gates
# run at trace time, so a contaminated artifact would otherwise warn on
# every jit trace while saying nothing new.
_dispatch_events_emitted: set[str] = set()


def warn_dispatch_event(family: str, sweep: str) -> bool:
    """Emit a rate-limited :class:`DispatchTableWarning` (once per
    ``family``) naming the contaminated sweep the gate refused to trust.

    Returns True when a warning was actually emitted."""
    if family in _dispatch_events_emitted:
        return False
    _dispatch_events_emitted.add(family)
    _warnings.warn(
        f'kfac-tpu dispatch: {family!r} threshold held at the conservative '
        f'XLA default — backing sweep {sweep!r} is latency-floor '
        'contaminated (re-derive kfac_tpu/ops/dispatch_thresholds.json '
        'from a clean one-dispatch sweep)',
        DispatchTableWarning, stacklevel=2,
    )
    return True


def reset_dispatch_warnings() -> None:
    """Forget emitted dispatch-gate events (tests)."""
    _dispatch_events_emitted.clear()
