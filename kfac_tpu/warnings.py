"""Warning categories (reference parity: kfac/warnings.py:6-9)."""

from __future__ import annotations


class ExperimentalFeatureWarning(Warning):
    """Feature is experimental and may change or underperform."""


class TPUPerformanceWarning(Warning):
    """Configuration known to be pathologically slow on TPU backends."""
