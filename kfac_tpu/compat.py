"""JAX version compatibility shims.

The framework targets the current JAX API surface; older installs get
adapters here, loaded from ``kfac_tpu/__init__.py`` before anything else
so every module (and the test suite, which imports ``kfac_tpu``) sees a
uniform API.

``jax.shard_map``: promoted out of ``jax.experimental.shard_map`` with two
renames — ``axis_names`` (the manual axes) replaced the complementary
``auto`` frozenset, and ``check_vma`` replaced ``check_rep``. On installs
without the top-level binding we install an adapter that accepts the new
spelling and translates.
"""

from __future__ import annotations

from typing import Any

import jax


def _install_shard_map() -> None:
    if hasattr(jax, 'shard_map'):
        return
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(
        f: Any,
        mesh: Any = None,
        in_specs: Any = None,
        out_specs: Any = None,
        axis_names: Any = None,
        check_vma: bool | None = None,
        **kwargs: Any,
    ):
        if axis_names is not None:
            kwargs['auto'] = frozenset(mesh.axis_names) - frozenset(
                axis_names
            )
        if check_vma is not None:
            kwargs['check_rep'] = check_vma
        return _legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    jax.shard_map = shard_map


_install_shard_map()
