"""JAX version compatibility shims.

The framework targets the current JAX API surface; older installs get
adapters here, loaded from ``kfac_tpu/__init__.py`` before anything else
so every module (and the test suite, which imports ``kfac_tpu``) sees a
uniform API.

``jax.shard_map``: promoted out of ``jax.experimental.shard_map`` with two
renames — ``axis_names`` (the manual axes) replaced the complementary
``auto`` frozenset, and ``check_vma`` replaced ``check_rep``. On installs
without the top-level binding we install an adapter that accepts the new
spelling and translates.

``jax.lax.pcast``: the varying-manual-axes annotation that newer JAX
requires inside ``shard_map`` bodies (replication is declared, not
inferred). Legacy installs infer replication instead, so the annotation
is semantically a no-op there — we install an identity and default the
``shard_map`` adapter to ``check_rep=False``, because the legacy checker
would otherwise reject out_specs whose varying-ness only the (absent)
annotations could prove.
"""

from __future__ import annotations

from typing import Any

import jax


def _install_shard_map() -> None:
    if hasattr(jax, 'shard_map'):
        return
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(
        f: Any,
        mesh: Any = None,
        in_specs: Any = None,
        out_specs: Any = None,
        axis_names: Any = None,
        check_vma: bool | None = None,
        **kwargs: Any,
    ):
        if axis_names is not None:
            kwargs['auto'] = frozenset(mesh.axis_names) - frozenset(
                axis_names
            )
        if check_vma is not None:
            kwargs['check_rep'] = check_vma
        else:
            kwargs.setdefault('check_rep', False)
        return _legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    jax.shard_map = shard_map


def _install_pcast() -> None:
    if hasattr(jax.lax, 'pcast'):
        return

    def pcast(x: Any, axis_name: Any, *, to: str | None = None) -> Any:
        del axis_name, to  # legacy shard_map infers replication
        return x

    jax.lax.pcast = pcast


_install_shard_map()
_install_pcast()
