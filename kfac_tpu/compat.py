"""JAX version compatibility shims.

The framework targets the current JAX API surface; older installs get
adapters here, loaded from ``kfac_tpu/__init__.py`` before anything else
so every module (and the test suite, which imports ``kfac_tpu``) sees a
uniform API.

``jax.shard_map``: promoted out of ``jax.experimental.shard_map`` with two
renames — ``axis_names`` (the manual axes) replaced the complementary
``auto`` frozenset, and ``check_vma`` replaced ``check_rep``. On installs
without the top-level binding we install an adapter that accepts the new
spelling and translates.

``jax.lax.pcast``: the varying-manual-axes annotation that newer JAX
requires inside ``shard_map`` bodies (replication is declared, not
inferred). Legacy installs infer replication instead, so the annotation
is semantically a no-op there — we install an identity and default the
``shard_map`` adapter to ``check_rep=False``, because the legacy checker
would otherwise reject out_specs whose varying-ness only the (absent)
annotations could prove.

``jax.typeof``: newer JAX's aval accessor (the dispatch heuristics read
``typeof(x).vma`` to thread manual-axes varying-ness into Pallas
out_shapes). Legacy installs alias it to ``core.get_aval``; legacy avals
carry no ``vma`` attribute, which downstream ``getattr(..., 'vma',
None)`` reads treat as "no annotation" — correct, because legacy
``shard_map`` infers replication instead of declaring it.

``jax.sharding.get_abstract_mesh``: the trace-context mesh probe that
``pallas_gate.manual_context`` uses to decide whether a raw
``pallas_call`` may run (fully-manual context) or dispatch must fall
back to XLA (partial-manual). Legacy installs never materialize an
abstract mesh during ``shard_map`` body tracing and the ``auto`` set is
dropped after staging, so the adapter records (mesh, manual axes) on a
thread-local stack around the body itself and the installed
``get_abstract_mesh`` answers from that stack with a duck-typed mesh
whose ``axis_types`` uses the new-style name→type mapping.
"""

from __future__ import annotations

import threading
from typing import Any

import jax


class _CompatAbstractMesh:
    """Duck-typed stand-in for the new-style abstract mesh: ``axis_names``
    plus the name→type ``axis_types`` mapping ``manual_context`` reads."""

    def __init__(self, axis_names: tuple, manual: frozenset):
        self.axis_names = tuple(axis_names)
        self.axis_types = {
            name: 'Manual' if name in manual else 'Auto'
            for name in self.axis_names
        }


_EMPTY_ABSTRACT_MESH = _CompatAbstractMesh((), frozenset())
_mesh_stack = threading.local()


def _install_shard_map() -> None:
    if hasattr(jax, 'shard_map'):
        return
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(
        f: Any,
        mesh: Any = None,
        in_specs: Any = None,
        out_specs: Any = None,
        axis_names: Any = None,
        check_vma: bool | None = None,
        **kwargs: Any,
    ):
        manual = (
            frozenset(mesh.axis_names)
            if axis_names is None
            else frozenset(axis_names)
        )
        if axis_names is not None:
            kwargs['auto'] = frozenset(mesh.axis_names) - manual
        if check_vma is not None:
            kwargs['check_rep'] = check_vma
        else:
            kwargs.setdefault('check_rep', False)

        # legacy installs drop the auto set after staging; record the
        # manual-axes context around the body so get_abstract_mesh (below)
        # can answer trace-time dispatch probes
        def body(*args: Any, **kw: Any):
            stack = getattr(_mesh_stack, 'stack', None)
            if stack is None:
                stack = _mesh_stack.stack = []
            stack.append(_CompatAbstractMesh(mesh.axis_names, manual))
            try:
                return f(*args, **kw)
            finally:
                stack.pop()

        return _legacy(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **kwargs
        )

    jax.shard_map = shard_map


def _install_typeof() -> None:
    if hasattr(jax, 'typeof'):
        return
    jax.typeof = jax.core.get_aval


def _install_get_abstract_mesh() -> None:
    if hasattr(jax.sharding, 'get_abstract_mesh'):
        return

    def get_abstract_mesh() -> _CompatAbstractMesh:
        stack = getattr(_mesh_stack, 'stack', None)
        return stack[-1] if stack else _EMPTY_ABSTRACT_MESH

    jax.sharding.get_abstract_mesh = get_abstract_mesh


def _install_pcast() -> None:
    if hasattr(jax.lax, 'pcast'):
        return

    def pcast(x: Any, axis_name: Any, *, to: str | None = None) -> Any:
        del axis_name, to  # legacy shard_map infers replication
        return x

    jax.lax.pcast = pcast


_install_shard_map()
_install_pcast()
_install_typeof()
_install_get_abstract_mesh()
