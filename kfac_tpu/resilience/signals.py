"""Preemption-signal handling: flag-setting handlers, no work in the
handler itself.

TPU preemption (and most cluster schedulers) deliver a SIGTERM with a
short grace window before the hard kill; operators poke long runs with
SIGUSR1 to snapshot state without stopping them. A signal handler that
does real work (checkpoint I/O, collectives) from interrupt context is a
deadlock machine, so the handlers here only record *which* signal
arrived; :class:`kfac_tpu.resilience.CheckpointManager` polls the flag at
step boundaries — a safe point where no jit computation or collective is
in flight — and performs the emergency blocking save there (rank 0
coordinates; the other hosts reach the same save through the
``multihost.allgather_scalars`` barrier in ``CheckpointManager.on_step``,
so a signal delivered to only one host still checkpoints the whole pod).

The signal table in ``docs/ROBUSTNESS.md`` is linted against
:data:`HANDLED_SIGNALS` by ``tools/lint_signals.py`` (run via
``make resilience``), so documented semantics cannot drift from the
handlers actually registered here.
"""

from __future__ import annotations

import contextlib
import dataclasses
import signal as _signal
from typing import Iterable, Iterator


@dataclasses.dataclass(frozen=True)
class SignalSpec:
    """Semantics of one handled signal.

    ``exits``: after the emergency checkpoint is durable, does training
    stop (:class:`~kfac_tpu.resilience.Preempted` is raised) or continue?
    """

    name: str
    exits: bool
    description: str


#: the signals :func:`install` handles by default, with their semantics —
#: the source of truth for the docs/ROBUSTNESS.md signal table
HANDLED_SIGNALS: dict[str, SignalSpec] = {
    'SIGTERM': SignalSpec(
        'SIGTERM', exits=True,
        description='preemption notice: flush an emergency blocking '
                    'checkpoint, then exit via Preempted',
    ),
    'SIGUSR1': SignalSpec(
        'SIGUSR1', exits=False,
        description='operator snapshot: flush an emergency blocking '
                    'checkpoint, training continues',
    ),
}

#: name of the most urgent signal seen and not yet consumed (exit signals
#: outrank continue signals; within a rank, latest delivery wins)
_pending: str | None = None

#: name of the signal whose emergency save is CURRENTLY in flight
#: (bracketed by :func:`save_in_flight` from
#: ``CheckpointManager.save_emergency``). Signal storms — schedulers
#: re-deliver SIGTERM every few seconds until the process dies — must
#: not re-arm the flag mid-save: the save is already running, and a
#: re-armed flag would re-enter ``save_emergency`` at the next boundary
#: (SIGUSR1) or leave a stale flag behind the Preempted unwind
#: (SIGTERM). Only an ESCALATION (an exit signal landing during a
#: continue-signal save) still latches.
_in_flight: str | None = None


def _handler_for(name: str):
    def _handler(signum, frame):  # noqa: ARG001 - signal handler signature
        global _pending
        if _in_flight is not None and not (
            HANDLED_SIGNALS[name].exits
            and not HANDLED_SIGNALS[_in_flight].exits
        ):
            return  # storm re-delivery during the save: already handled
        if _pending is None or (
            HANDLED_SIGNALS[name].exits
            and not HANDLED_SIGNALS[_pending].exits
        ):
            _pending = name
    _handler.__kfac_signal__ = name  # lets tests identify our handlers
    return _handler


@contextlib.contextmanager
def save_in_flight(name: str) -> Iterator[None]:
    """Mark an emergency save for ``name`` as running (handler-visible).

    While active, re-deliveries of ``name`` (or anything that does not
    escalate over it) are dropped in the handler — idempotence under
    signal storms. Re-entrant: an escalated save nested inside a
    continue-signal save restores the outer marker on exit. Assigning a
    str is atomic under the GIL and handlers only read it, so no
    masking/locking is needed.
    """
    global _in_flight
    if name not in HANDLED_SIGNALS:
        raise ValueError(
            f'unknown preemption signal {name!r}; handled signals: '
            f'{sorted(HANDLED_SIGNALS)}'
        )
    previous = _in_flight
    _in_flight = name
    try:
        yield
    finally:
        _in_flight = previous


class SignalHandle:
    """Installed-handler record; ``uninstall()`` restores what was there
    before (context-manager friendly)."""

    def __init__(self, previous: list[tuple[int, object]]) -> None:
        self._previous = previous

    def uninstall(self) -> None:
        while self._previous:
            signum, prev = self._previous.pop()
            _signal.signal(signum, prev)

    def __enter__(self) -> 'SignalHandle':
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.uninstall()


def install(
    signals: Iterable[str] = ('SIGTERM', 'SIGUSR1'),
) -> SignalHandle:
    """Install flag-setting handlers for the named signals.

    Only signals listed in :data:`HANDLED_SIGNALS` are accepted (their
    semantics are documented and linted); returns a :class:`SignalHandle`
    whose ``uninstall()`` restores the previous handlers. Must run on the
    main thread (a CPython ``signal.signal`` constraint).
    """
    previous: list[tuple[int, object]] = []
    handle = SignalHandle(previous)
    try:
        for name in signals:
            if name not in HANDLED_SIGNALS:
                raise ValueError(
                    f'unknown preemption signal {name!r}; handled signals: '
                    f'{sorted(HANDLED_SIGNALS)}'
                )
            signum = getattr(_signal, name)
            previous.append((signum, _signal.getsignal(signum)))
            _signal.signal(signum, _handler_for(name))
    except Exception:
        handle.uninstall()
        raise
    return handle


def preemption_requested() -> str | None:
    """The pending signal name, or None. Does not clear the flag."""
    return _pending


def consume() -> str | None:
    """Return and clear the pending signal flag."""
    global _pending
    name, _pending = _pending, None
    return name


def exits(name: str) -> bool:
    """Whether the named signal's semantics end training after the save."""
    return HANDLED_SIGNALS[name].exits


def save_in_flight_signal() -> str | None:
    """The signal whose emergency save is currently running, or None."""
    return _in_flight


def reset() -> None:
    """Clear the pending and in-flight flags (tests)."""
    global _pending, _in_flight
    _pending = None
    _in_flight = None
