"""Preemption-safe training: checkpoint autopilot + signal handling.

``CheckpointManager`` owns a keep-N rotation of step-numbered checkpoint
directories with an atomically-updated ``LATEST`` pointer, drives
periodic async saves from the Trainer step paths, flushes an emergency
blocking save when a preemption signal arrives, and restores the newest
*good* checkpoint with last-good fallback and elastic cross-topology
migration. See docs/ROBUSTNESS.md ("Preemption & resume").

``FleetController`` closes the loop into a self-driving fleet: restores
onto a changed topology re-tune the layout through the autotuner's
cost-model-only fast path, and sustained cross-host drift (flight-
recorder skew columns) triggers a pod-coordinated live layout migration
at the next checkpoint boundary. See docs/ROBUSTNESS.md ("Self-driving
fleet").
"""

from kfac_tpu.resilience import signals
from kfac_tpu.resilience.fleet import FleetConfig, FleetController
from kfac_tpu.resilience.manager import (
    CheckpointManager,
    Preempted,
    RestoreResult,
)

__all__ = [
    'CheckpointManager',
    'FleetConfig',
    'FleetController',
    'Preempted',
    'RestoreResult',
    'signals',
]
