"""Preemption-safe training: checkpoint autopilot + signal handling.

``CheckpointManager`` owns a keep-N rotation of step-numbered checkpoint
directories with an atomically-updated ``LATEST`` pointer, drives
periodic async saves from the Trainer step paths, flushes an emergency
blocking save when a preemption signal arrives, and restores the newest
*good* checkpoint with last-good fallback and elastic cross-topology
migration. See docs/ROBUSTNESS.md ("Preemption & resume").

``FleetController`` closes the loop into a self-driving fleet: restores
onto a changed topology re-tune the layout through the autotuner's
cost-model-only fast path, and sustained cross-host drift (flight-
recorder skew columns) triggers a pod-coordinated live layout migration
at the next checkpoint boundary. See docs/ROBUSTNESS.md ("Self-driving
fleet").

``ChaosConductor`` turns all of the above into a measured claim: it
drives a real multi-process gloo pod through scripted or seeded
preemption storms (SIGTERM waves, torn checkpoints, topology
shrink/grow, injected skew) and reconciles per-rank event streams into
recovery SLO rows — downtime steps, recovery wall-clock, restore
fallback depth, zero-divergence vs an uninterrupted control run —
failing loudly when a budget is blown. See docs/ROBUSTNESS.md ("Chaos
harness").
"""

from kfac_tpu.resilience import signals
from kfac_tpu.resilience.chaos import (
    ChaosConductor,
    ChaosConfig,
    ChaosError,
    ChaosReport,
)
from kfac_tpu.resilience.fleet import FleetConfig, FleetController
from kfac_tpu.resilience.manager import (
    CheckpointManager,
    Preempted,
    RestoreResult,
)

__all__ = [
    'ChaosConductor',
    'ChaosConfig',
    'ChaosError',
    'ChaosReport',
    'CheckpointManager',
    'FleetConfig',
    'FleetController',
    'Preempted',
    'RestoreResult',
    'signals',
]
