"""Preemption-safe training: checkpoint autopilot + signal handling.

``CheckpointManager`` owns a keep-N rotation of step-numbered checkpoint
directories with an atomically-updated ``LATEST`` pointer, drives
periodic async saves from the Trainer step paths, flushes an emergency
blocking save when a preemption signal arrives, and restores the newest
*good* checkpoint with last-good fallback and elastic cross-topology
migration. See docs/ROBUSTNESS.md ("Preemption & resume").
"""

from kfac_tpu.resilience import signals
from kfac_tpu.resilience.manager import (
    CheckpointManager,
    Preempted,
    RestoreResult,
)

__all__ = [
    'CheckpointManager',
    'Preempted',
    'RestoreResult',
    'signals',
]
