"""Checkpoint autopilot: keep-N rotation, atomic LATEST pointer,
periodic async saves, emergency flush on preemption, last-good fallback
restore.

The primitives live in :mod:`kfac_tpu.checkpoint` (orbax async save,
layout manifests, cross-layout factor migration); this module composes
them into a loop that survives the pod-scale failure modes: SIGTERM in
the middle of an async save, a torn write in the newest checkpoint, a
restore onto a different topology. Invariants:

- Every save goes to a FRESH step-numbered directory
  (``<root>/step_00000042/ckpt``), so no write ever touches the bytes of
  an existing checkpoint.
- The ``LATEST`` pointer is a one-line file updated by atomic
  ``os.replace`` and committed only after ``wait_until_finished()`` — a
  crash at any instant leaves the previous pointer valid and pointing at
  a durable checkpoint.
- Rotation pruning keeps the newest ``keep`` committed checkpoints and
  never deletes the ``LATEST`` target.
- :meth:`CheckpointManager.restore_latest` walks newest → oldest,
  validating each candidate (orbax commit metadata, manifest sidecar,
  and ``checkpoint.restore``'s factor finiteness/shape checks) and falls
  back to the last good one with a rate-limited warning.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import time
from typing import Any, Callable, NamedTuple

import jax

from kfac_tpu import checkpoint as checkpoint_lib
from kfac_tpu.resilience import signals as signals_lib
from kfac_tpu.warnings import CheckpointResilienceWarning

import warnings as _warnings

_STEP_PREFIX = 'step_'
_LATEST = 'LATEST'
_CKPT_NAME = 'ckpt'

#: emergency codes carried through the multihost barrier (max wins):
#: 0 = no request, 1 = save-and-continue, 2 = save-and-exit
_CODE_NONE, _CODE_CONTINUE, _CODE_EXIT = 0, 1, 2

#: Introspectable save-protocol table for the kfaclint pod tier
#: (KFL305). The pod rules parse this literal straight from the AST
#: (never importing this module), model-check it under the fault
#: alphabet (crash after any step, signal re-entry), and cross-check
#: every ``barrier``/``wait`` step against the protocol ops actually
#: reachable from :meth:`CheckpointManager.save` — so the table cannot
#: rot away from the code, and deleting the real barrier breaks the
#: lint even with the table intact. Step order is the LOGICAL commit
#: order; the async path defers wait+commit to the next
#: ``on_step``/``finalize`` but never reorders them. Keep it a pure
#: literal.
SAVE_PROTOCOL = {
    'machine': 'sequence',
    'name': 'checkpoint-save',
    'function': 'CheckpointManager.save',
    'steps': (
        {'op': 'flush_pending', 'rank': 'all', 'kind': 'host'},
        {'op': 'clear_stale_dir', 'rank': 0, 'kind': 'mutate',
         'effect': 'mutate_dir'},
        {'op': 'barrier', 'rank': 'all', 'kind': 'barrier'},
        {'op': 'write_checkpoint', 'rank': 'all', 'kind': 'mutate',
         'effect': 'write_step_dir'},
        {'op': 'wait_until_finished', 'rank': 'all', 'kind': 'wait'},
        {'op': 'commit_latest', 'rank': 0, 'kind': 'mutate',
         'effect': 'point_latest'},
    ),
}


class Preempted(RuntimeError):
    """Raised by :meth:`CheckpointManager.on_step` after a successful
    emergency save for an exit-semantics signal (SIGTERM): the state is
    durable, unwind the training loop now — the platform's hard kill is
    coming. ``step`` (and the rotation entry) is the pod-agreed step
    from the coordination barrier, identical on every host."""

    def __init__(self, signal_name: str, step: int, path: str) -> None:
        super().__init__(
            f'preempted by {signal_name} at step {step}; emergency '
            f'checkpoint is durable at {path!r} — resume with '
            'CheckpointManager.restore_latest()'
        )
        self.signal_name = signal_name
        self.step = step
        self.path = path


class RestoreResult(NamedTuple):
    """What :meth:`CheckpointManager.restore_latest` hands back."""

    state: Any
    extra: dict[str, Any]
    step: int
    path: str


class _PendingSave(NamedTuple):
    handle: Any
    step: int


def _host_step(state: Any) -> int:
    """Host int of an engine state's step counter (dict states included)."""
    step = state['step'] if isinstance(state, dict) else state.step
    return int(jax.device_get(step))


def _split_train_state(state: Any) -> tuple[Any, dict[str, Any] | None]:
    """(engine_state, extra-trees) from either a Trainer ``TrainState``
    or a bare engine state (duck-typed on ``kfac_state``)."""
    if hasattr(state, 'kfac_state'):
        extra: dict[str, Any] = {
            'params': state.params, 'opt_state': state.opt_state,
        }
        if state.model_state is not None:
            extra['model_state'] = state.model_state
        return state.kfac_state, extra
    return state, None


class CheckpointManager:
    """Owns a rotation of step-numbered checkpoint directories.

    Args:
        directory: rotation root (created if missing). Must be a local or
            shared filesystem path — each step's checkpoint lands in
            ``<directory>/step_<NNNNNNNN>/ckpt``.
        engine: the preconditioner engine (dense ``KFACPreconditioner``
            or ``parallel.DistributedKFAC``); passed through to
            ``checkpoint.save(engine=...)`` so every rotation entry
            carries a layout manifest and restores elastically.
        save_interval_steps: periodic-save cadence for :meth:`on_step`
            (``None`` disables periodic saves; signals still work).
        keep: committed checkpoints retained by the rotation.
        async_save: periodic saves return immediately and commit their
            ``LATEST`` pointer at the next :meth:`on_step` /
            :meth:`finalize` (emergency saves always block).
        install_signals: install the flag-setting handlers from
            :mod:`kfac_tpu.resilience.signals` for these signal names at
            construction (``()`` to manage handlers yourself).
        coordinate_every: multi-host only — every this-many steps,
            :meth:`on_step` runs the ``multihost.allgather_scalars``
            barrier that propagates one host's preemption signal to the
            whole pod. This is the pod's reaction latency: a signal seen
            between coordinated steps stays pending until the next one
            (every host enters the barrier on exactly the same steps, so
            the collective always pairs up). 1 (default) reacts within a
            step; raise it if the per-step DCN gather matters. Must be
            identical on all hosts.
        max_retries / backoff_base / backoff_max: transient-I/O retry
            policy — each failed save attempt retries after
            ``min(backoff_max, backoff_base * 2**attempt)`` seconds.
    """

    def __init__(
        self,
        directory: str | os.PathLike[str],
        engine: Any = None,
        *,
        save_interval_steps: int | None = 100,
        keep: int = 3,
        async_save: bool = True,
        install_signals: tuple[str, ...] = ('SIGTERM', 'SIGUSR1'),
        coordinate_every: int = 1,
        max_retries: int = 3,
        backoff_base: float = 0.5,
        backoff_max: float = 8.0,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        if keep < 1:
            raise ValueError(f'keep must be >= 1, got {keep}')
        if save_interval_steps is not None and save_interval_steps < 1:
            raise ValueError(
                'save_interval_steps must be >= 1 or None, got '
                f'{save_interval_steps}'
            )
        if coordinate_every < 1:
            raise ValueError(
                f'coordinate_every must be >= 1, got {coordinate_every}'
            )
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.engine = engine
        self.save_interval_steps = save_interval_steps
        self.keep = int(keep)
        self.async_save = bool(async_save)
        self.coordinate_every = int(coordinate_every)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._sleep = sleep
        self._pending: _PendingSave | None = None
        self._last_saved_step: int | None = None
        self._warned_paths: set[str] = set()
        self._signal_handle = (
            signals_lib.install(install_signals) if install_signals else None
        )

    # ------------------------------------------------------------ rotation

    def step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f'{_STEP_PREFIX}{step:08d}')

    def checkpoint_path(self, step: int) -> str:
        return os.path.join(self.step_dir(step), _CKPT_NAME)

    def _latest_path(self) -> str:
        return os.path.join(self.directory, _LATEST)

    def rotation_steps(self) -> list[int]:
        """Step numbers present in the rotation, newest first (presence =
        the step dir exists; commit state is checked per candidate)."""
        steps = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in entries:
            if name.startswith(_STEP_PREFIX):
                try:
                    steps.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(steps, reverse=True)

    def latest_step(self) -> int | None:
        """The committed ``LATEST`` pointer's step, or None.

        A torn pointer — truncated or overwritten with garbage bytes by
        a crashing writer or filesystem rollback — is treated as absent
        (the bytes are read raw and decoded leniently: a torn pointer
        must degrade to the rotation-scan fallback, never crash the
        restore)."""
        try:
            with open(self._latest_path(), 'rb') as f:
                name = f.read().decode('utf-8', errors='replace').strip()
        except OSError:
            return None
        if not name.startswith(_STEP_PREFIX):
            return None
        try:
            return int(name[len(_STEP_PREFIX):])
        except ValueError:
            return None

    def _is_committed(self, step: int) -> bool:
        """Orbax commit markers present for the rotation entry."""
        ckpt = self.checkpoint_path(step)
        return os.path.isdir(ckpt) and all(
            os.path.exists(os.path.join(ckpt, marker))
            for marker in ('_CHECKPOINT_METADATA', '_METADATA')
        )

    def _commit(self, step: int) -> None:
        """Atomically point ``LATEST`` at ``step``; prune the rotation.

        Rank 0 only (the rotation lives on a shared filesystem; on
        single-host runs rank 0 is the only rank). Called strictly after
        ``wait_until_finished()``, so the pointer can never name an
        uncommitted checkpoint.
        """
        self._last_saved_step = step
        if jax.process_index() != 0:
            return
        latest = self._latest_path()
        tmp = f'{latest}.tmp.{os.getpid()}'
        with open(tmp, 'w') as f:
            f.write(os.path.basename(self.step_dir(step)) + '\n')
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, latest)
        self._prune(protect=step)

    def _prune(self, protect: int) -> None:
        """Drop rotation entries beyond ``keep``, never the protected
        (LATEST) step, and never an uncommitted dir newer than the
        newest committed step (an async save may still be writing it).
        Uncommitted dirs *older* than the newest committed step can no
        longer be in-flight (saves are sequential and commit before the
        next one starts) — they are torn corpses from crashed attempts,
        pruned so the rotation walk stays bounded."""
        steps = self.rotation_steps()
        committed = [s for s in steps if self._is_committed(s)]
        for step in committed[self.keep:]:
            if step == protect:
                continue
            shutil.rmtree(self.step_dir(step), ignore_errors=True)
        if committed:
            newest, live = committed[0], set(committed)
            for step in steps:
                if step < newest and step not in live and step != protect:
                    shutil.rmtree(self.step_dir(step), ignore_errors=True)

    # --------------------------------------------------------------- saving

    def _with_retries(self, what: str, fn: Callable[[], Any]) -> Any:
        for attempt in range(self.max_retries + 1):
            try:
                return fn()
            except OSError as exc:
                if attempt == self.max_retries:
                    raise
                delay = min(
                    self.backoff_max, self.backoff_base * (2 ** attempt)
                )
                _warnings.warn(
                    f'{what} failed with transient I/O error ({exc}); '
                    f'retry {attempt + 1}/{self.max_retries} in '
                    f'{delay:.1f}s',
                    CheckpointResilienceWarning,
                    stacklevel=3,
                )
                self._sleep(delay)

    def _flush_pending(self) -> None:
        """Finish an in-flight async save and commit its LATEST pointer."""
        if self._pending is None:
            return
        pending, self._pending = self._pending, None
        self._with_retries(
            f'finishing async checkpoint for step {pending.step}',
            pending.handle.wait_until_finished,
        )
        self._commit(pending.step)

    def save(
        self,
        state: Any,
        step: int | None = None,
        block: bool | None = None,
    ) -> str:
        """Save ``state`` (a Trainer ``TrainState`` or a bare engine
        state) into a fresh rotation entry; returns the checkpoint path.

        Blocking saves commit their ``LATEST`` pointer before returning;
        async saves commit at the next :meth:`on_step` /
        :meth:`finalize` — either way the pointer only ever moves after
        ``wait_until_finished()``.
        """
        self._flush_pending()
        kstate, extra = _split_train_state(state)
        if step is None:
            step = _host_step(kstate)
        block = (not self.async_save) if block is None else block
        sdir = self.step_dir(step)
        from kfac_tpu.parallel import multihost

        if multihost.process_index() == 0 and os.path.exists(sdir):
            # a dead earlier attempt at this step (crashed mid-write, or a
            # re-save after restore): the rotation never reuses bytes, so
            # clear it and write fresh. Rank 0 only — on a shared
            # filesystem concurrent rmtrees race each other (entries
            # vanishing underneath a peer's walk raise OSError)
            self._with_retries(
                f'clearing stale rotation entry for step {step}',
                lambda: shutil.rmtree(sdir),
            )
        if multihost.process_count() > 1:
            # unconditional (the per-host exists-check may disagree under
            # filesystem lag): no host starts writing until rank 0's
            # clear above has finished
            multihost.barrier(f'kfac-resilience-save-{step}')
        path = self.checkpoint_path(step)

        def attempt():
            os.makedirs(sdir, exist_ok=True)
            return checkpoint_lib.save(
                path, kstate, extra=extra, engine=self.engine,
                wait=block,
            )

        handle = self._with_retries(
            f'checkpoint save for step {step}', attempt
        )
        if block:
            self._commit(step)
        else:
            self._pending = _PendingSave(handle, step)
        return path

    def save_emergency(
        self, state: Any, reason: str = 'signal', step: int | None = None,
    ) -> str:
        """Blocking save + commit for preemption / health events.

        ``step`` defaults to the state's own counter; multi-host callers
        must pass the same value on every host (``on_step`` passes the
        pod-agreed step from the coordination barrier, so skewed hosts
        still land in one rotation entry).

        Idempotent per step: if this step is already durable in the
        rotation (e.g. the periodic async save just committed it), the
        existing checkpoint is pointed at and no second write happens —
        the SIGTERM grace window is too precious to spend re-writing
        bytes that are already safe.

        Signal storms (schedulers re-deliver SIGTERM until the process
        dies) are dropped for the save's duration: the whole body runs
        under :func:`signals.save_in_flight`, so a re-delivery of the
        triggering signal cannot re-arm the flag and re-enter here —
        only an escalation (SIGTERM during a SIGUSR1 save) still
        latches.
        """
        # only a SIGNAL-driven save suppresses re-deliveries; a health or
        # fleet-migration save must still latch an incoming SIGTERM (the
        # preemption notice outlives this save)
        bracket = (
            signals_lib.save_in_flight(reason)
            if reason in signals_lib.HANDLED_SIGNALS
            else contextlib.nullcontext()
        )
        with bracket:
            self._flush_pending()
            if step is None:
                kstate, _ = _split_train_state(state)
                step = _host_step(kstate)
            _warnings.warn(
                f'emergency checkpoint requested at step {step} ({reason})',
                CheckpointResilienceWarning,
                stacklevel=2,
            )
            if self._is_committed(step):
                if self._last_saved_step != step:
                    self._commit(step)
                return self.checkpoint_path(step)
            return self.save(state, step=step, block=True)

    # -------------------------------------------------------------- driving

    def _poll_emergency(self, step: int) -> tuple[int, int]:
        """Local signal flag -> pod-wide agreed ``(code, step)``.

        Multi-host, barrier participation depends ONLY on data every
        host computes identically (the step cadence): a signal seen on
        an off-cadence step stays pending until the next coordinated
        step, so the allgather always pairs up host-for-host.
        ``coordinate_every`` is therefore the pod's reaction latency to
        a preemption signal, never a correctness knob.
        """
        local = signals_lib.preemption_requested()
        code = _CODE_NONE
        if local is not None:
            code = _CODE_EXIT if signals_lib.exits(local) else _CODE_CONTINUE
        from kfac_tpu.parallel import multihost

        if multihost.process_count() > 1:
            if step % self.coordinate_every != 0:
                # defer — acting on the local flag here would either skip
                # the barrier (per-host saves at divergent steps) or enter
                # it on a step where unsignaled hosts don't gather
                return _CODE_NONE, step
            code, step = multihost.agree_emergency(code, step)
        return code, step

    def on_step(self, state: Any, step: int | None = None) -> str | None:
        """Drive the autopilot from a training loop, once per step.

        Checks the preemption flag (coordinating across hosts), flushes
        an emergency blocking save when one is pending — raising
        :class:`Preempted` for exit-semantics signals (SIGTERM) once the
        state is durable — and otherwise starts the periodic
        (default async) save on cadence. Returns the path saved this
        call, or None. ``kfac_tpu.Trainer`` calls this automatically when
        constructed with ``checkpoints=<manager>``.
        """
        kstate, _ = _split_train_state(state)
        if step is None:
            step = _host_step(kstate)
        code, agreed_step = self._poll_emergency(step)
        if code != _CODE_NONE:
            local = signals_lib.consume()
            if code == _CODE_EXIT and (
                local is None or not signals_lib.exits(local)
            ):
                # the pod outranks the local view: another host saw the
                # exit signal — name the exit cause, not whatever
                # continue-semantics signal this host happened to catch
                name = 'SIGTERM'
            else:
                name = local or 'SIGUSR1'
            path = self.save_emergency(state, reason=name, step=agreed_step)
            if code == _CODE_EXIT:
                raise Preempted(name, agreed_step, path)
            return path
        if (
            self.save_interval_steps is not None
            and step > 0
            and step % self.save_interval_steps == 0
            and step != self._last_saved_step
            and (self._pending is None or self._pending.step != step)
        ):
            return self.save(state, step=step)
        return None

    # ------------------------------------------------------------ restoring

    def restore_latest(
        self,
        engine: Any = None,
        extra_template: dict[str, Any] | None = None,
    ) -> RestoreResult | None:
        """Restore the newest good checkpoint, falling back across the
        rotation.

        Candidates are walked newest → oldest, starting from the
        ``LATEST`` pointer's target. Each is validated before use: orbax
        commit metadata present, layout-manifest sidecar present (its
        absence is tolerated with a warning — same-layout restores still
        work), and the restore itself runs ``checkpoint.restore``'s
        factor finiteness/shape validation. A candidate failing any check
        falls back to the next older one with a rate-limited
        :class:`CheckpointResilienceWarning`. After a successful restore,
        all hosts verify they agreed on the restored step.

        Returns None when the rotation holds no restorable checkpoint.
        ``engine`` defaults to the manager's engine — pass a different
        one for elastic restore onto a new topology/layout.
        """
        engine = self.engine if engine is None else engine
        if engine is None:
            raise ValueError(
                'restore_latest needs an engine: construct the manager '
                'with engine=..., or pass one explicitly'
            )
        seen: set[int] = set()
        candidates: list[int] = []
        latest = self.latest_step()
        if latest is not None:
            candidates.append(latest)
            seen.add(latest)
        for step in self.rotation_steps():
            if step not in seen:
                candidates.append(step)
        for step in candidates:
            path = self.checkpoint_path(step)
            if not self._is_committed(step):
                self._warn_fallback(
                    path, 'missing orbax commit metadata (torn or '
                          'in-flight write)'
                )
                continue
            try:
                state, extra = checkpoint_lib.restore(
                    path, engine, extra_template=extra_template
                )
            except KeyboardInterrupt:
                raise
            except Exception as exc:
                self._warn_fallback(path, f'{type(exc).__name__}: {exc}')
                continue
            restored_step = _host_step(
                state if not hasattr(state, 'kfac_state') else
                state.kfac_state
            )
            from kfac_tpu.parallel import multihost

            multihost.assert_same_step(restored_step)
            self._last_saved_step = restored_step
            return RestoreResult(state, extra, restored_step, path)
        return None

    def _warn_fallback(self, path: str, why: str) -> None:
        if path in self._warned_paths:
            return
        self._warned_paths.add(path)
        _warnings.warn(
            f'checkpoint candidate {path!r} is unusable ({why}); falling '
            'back to the previous rotation entry',
            CheckpointResilienceWarning,
            stacklevel=3,
        )

    # ------------------------------------------------------------- lifecycle

    def finalize(self) -> None:
        """Flush any in-flight async save (commit its pointer)."""
        self._flush_pending()

    def close(self) -> None:
        """Finalize and restore any signal handlers this manager
        installed."""
        self.finalize()
        if self._signal_handle is not None:
            self._signal_handle.uninstall()
            self._signal_handle = None

    def __enter__(self) -> 'CheckpointManager':
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
