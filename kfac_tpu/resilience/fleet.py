"""Self-driving elastic fleet: retune-on-restore + drift-triggered
live layout migration.

KAISA's premise is that the layout (one scalar, the gradient-worker
fraction, plus the bucket/transport knobs hanging off it) should track
the *deployment*, not a hand-config. Two deployment events break a
hand-picked — or even a tuned — layout mid-job:

1. **Preemption onto a different topology.** A :class:`~kfac_tpu
   .autotune.TunedPlan` is fingerprint-guarded, so restoring a job onto
   a resized pod silently discards the plan and falls back to defaults
   (``resolve_auto_layout``). The fleet controller instead re-runs the
   autotuner's **cost-model-only fast path** (``measure=False`` — the
   analytic model ranks the same candidate grid, no trial engines, no
   devices timed, deterministic and instant), rebuilds the engine under
   the fresh plan, and restores elastically through the rotation's
   layout manifests (``CheckpointManager.restore_latest(engine=...)``).
   Retune attempts retry with exponential backoff; if the tuned restore
   itself fails, the controller falls back to the canonical layout so
   the job always comes back up.

2. **Comms drift in steady state.** A long-running job's cross-host
   skew (stragglers, congested links) makes the once-optimal layout
   stale. The controller watches the flight recorder's cross-host skew
   columns (``drain_flight``'s ``skew_min/max/mean`` per headline key)
   against configurable thresholds; sustained drift triggers a
   model-only retune, and — when the retuned knobs actually differ —
   a pod-coordinated live migration at the **next checkpoint
   boundary**: blocking save → rebuild engine under the new plan →
   elastic restore → resume. Every host votes on the outcome through
   :func:`kfac_tpu.parallel.multihost.agree_decision`; any host's
   failure aborts the migration pod-wide.

Rollback semantics: the migration mutates NOTHING until it is verified
— the old engine, the in-memory TrainState, and the manager's engine
binding are only swapped after the elastic restore succeeded on every
host at the expected step. An abort therefore *is* the rollback:
training continues on the last-good layout and state bit-for-bit, the
pending plan is dropped, and a cooldown suppresses immediate re-arming.

Wiring: ``Trainer(fleet=FleetController(...))`` drives
:meth:`FleetController.on_step` from all four step paths and delegates
``restore_latest`` to :meth:`FleetController.restore_elastic`. See
docs/ROBUSTNESS.md ("Self-driving fleet").
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Sequence

import jax

from kfac_tpu import warnings as warnings_lib
from kfac_tpu.autotune import model as model_lib
from kfac_tpu.autotune import plan as plan_lib
from kfac_tpu.autotune import search as search_lib
from kfac_tpu.observability import flight_recorder as flight_lib
from kfac_tpu.parallel import multihost
from kfac_tpu.resilience import manager as manager_lib

#: search.autotune keyword arguments a controller may constrain
#: (everything else about the fast path is fixed: measure=False, the
#: live world size, the controller's HardwareSpec)
SEARCH_OVERRIDE_KEYS = (
    'fractions', 'granularities', 'transports', 'inv_cadences', 'top_k',
)

#: the plan artifact's filename inside the checkpoint rotation directory
#: — the plan travels WITH the rotation, so a restore on a new topology
#: finds the layout the job was actually running
PLAN_FILENAME = 'PLAN.json'

#: Introspectable migration state machine for the kfaclint pod tier
#: (KFL305). The pod rules parse this literal from the AST (never
#: importing this module) and model-check it under the fault alphabet
#: (crash at any state, vote outcome): every state reachable, both vote
#: outcomes handled wherever one is, controller state mutated ONLY on a
#: ``vote-commit`` transition, and abort transitions mutating nothing —
#: the mutate-nothing-until-verified contract of
#: :meth:`FleetController._maybe_migrate` as a checkable artifact. The
#: declared ``vote_op`` is additionally cross-checked against the ops
#: reachable from ``_maybe_migrate``, so dropping the real
#: ``agree_decision`` call breaks the lint even with the table intact.
#: Keep it a pure literal.
MIGRATION_PROTOCOL = {
    'machine': 'state',
    'name': 'fleet-migration',
    'function': 'FleetController._maybe_migrate',
    'vote_op': 'agree_decision',
    'states': ('idle', 'armed', 'boundary', 'committed', 'aborted'),
    'initial': 'idle',
    'transitions': (
        {'from': 'idle', 'event': 'drift', 'to': 'armed', 'mutates': ()},
        {'from': 'armed', 'event': 'checkpoint-boundary', 'to': 'boundary',
         'mutates': ()},
        {'from': 'boundary', 'event': 'vote-commit', 'to': 'committed',
         'mutates': ('plan', 'engine', 'train_state')},
        {'from': 'boundary', 'event': 'vote-abort', 'to': 'aborted',
         'mutates': ()},
        {'from': 'committed', 'event': 'cooldown', 'to': 'idle',
         'mutates': ()},
        {'from': 'aborted', 'event': 'cooldown', 'to': 'idle',
         'mutates': ()},
    ),
}


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Policy knobs of the self-driving fleet controller.

    All steady-state cadences are in engine steps. The KFL106 lint pins
    the knob table in docs/ROBUSTNESS.md to these fields.

    Args:
        check_every: drift-check cadence — every this-many steps the
            controller drains the flight recorder and evaluates the skew
            columns. Multi-host, the drain itself is one DCN gather, so
            this is also the fleet's added collective cadence.
        drift_keys: flight-recorder record keys whose cross-host skew is
            watched (each needs ``skew_min/max/mean`` columns, i.e. must
            be in the drain's skew keys — the controller's default drain
            requests exactly these).
        drift_threshold: relative skew ``(skew_max - skew_min) /
            |skew_mean|`` above which a window counts as drifted.
        drift_window: records (newest-first) averaged per drift check;
            checks are skipped until the ring holds a full window.
        drift_patience: consecutive over-threshold checks required
            before a retune triggers — one straggling drain must not
            re-layout the job.
        cooldown_steps: steps after any fleet event (migration, abort,
            failed or no-op retune) during which drift checks are
            suppressed, bounding the worst-case migration rate.
        retune_max_retries: extra cost-model retune attempts after the
            first failure.
        retune_backoff_base: first retry delay, seconds; attempt ``k``
            waits ``min(backoff_max, base * 2**k)``.
        retune_backoff_max: retry delay ceiling, seconds.
    """

    check_every: int = 16
    drift_keys: tuple[str, ...] = ('grad_norm', 'loss')
    drift_threshold: float = 0.5
    drift_window: int = 4
    drift_patience: int = 2
    cooldown_steps: int = 64
    retune_max_retries: int = 2
    retune_backoff_base: float = 0.5
    retune_backoff_max: float = 8.0

    def __post_init__(self) -> None:
        if self.check_every < 1:
            raise ValueError(
                f'check_every must be >= 1, got {self.check_every}'
            )
        if not self.drift_keys:
            raise ValueError('drift_keys must name at least one record key')
        object.__setattr__(self, 'drift_keys', tuple(self.drift_keys))
        if self.drift_threshold <= 0:
            raise ValueError(
                f'drift_threshold must be > 0, got {self.drift_threshold}'
            )
        if self.drift_window < 1:
            raise ValueError(
                f'drift_window must be >= 1, got {self.drift_window}'
            )
        if self.drift_patience < 1:
            raise ValueError(
                f'drift_patience must be >= 1, got {self.drift_patience}'
            )
        if self.cooldown_steps < 0:
            raise ValueError(
                f'cooldown_steps must be >= 0, got {self.cooldown_steps}'
            )
        if self.retune_max_retries < 0:
            raise ValueError(
                'retune_max_retries must be >= 0, got '
                f'{self.retune_max_retries}'
            )
        if self.retune_backoff_base <= 0 or self.retune_backoff_max <= 0:
            raise ValueError('retune backoff delays must be > 0')


class FleetController:
    """Owns the layout lifecycle of one training job.

    Args:
        manager: the :class:`~kfac_tpu.resilience.CheckpointManager`
            whose rotation the fleet saves into and restores from. The
            controller takes over its ``engine`` binding.
        config: :class:`FleetConfig` policy knobs.
        plan: initial tuned plan (TunedPlan / JSON dict / path). Default:
            the rotation directory's ``PLAN.json`` when present,
            otherwise the controller tunes one at :meth:`attach` (reason
            ``'startup'``).
        plan_path: where (re)tuned plans are persisted (rank 0, atomic
            write). Default: ``PLAN.json`` inside the manager's rotation
            directory.
        hardware: :class:`~kfac_tpu.autotune.model.HardwareSpec` fed to
            the cost model.
        search_overrides: optional :data:`SEARCH_OVERRIDE_KEYS` kwargs
            constraining every retune's candidate grid (an operator's
            standing layout constraints).
        drain: flight-recorder drain ``drain(state) -> records``;
            default drains with ``skew_keys=config.drift_keys``.
            Injectable for tests/bench (``testing.faults.skewed_drain``).
        sleep: retune-backoff sleep (injectable for tests).
    """

    def __init__(
        self,
        manager: Any,
        config: FleetConfig | None = None,
        *,
        plan: Any = None,
        plan_path: str | os.PathLike[str] | None = None,
        hardware: model_lib.HardwareSpec | None = None,
        search_overrides: dict[str, Any] | None = None,
        drain: Callable[[Any], list[dict[str, Any]]] | None = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.manager = manager
        self.config = config if config is not None else FleetConfig()
        self.hardware = (
            hardware if hardware is not None else model_lib.HardwareSpec()
        )
        self.search_overrides = dict(search_overrides or {})
        unknown = set(self.search_overrides) - set(SEARCH_OVERRIDE_KEYS)
        if unknown:
            raise ValueError(
                f'unknown search_overrides {sorted(unknown)}; expected a '
                f'subset of {SEARCH_OVERRIDE_KEYS}'
            )
        self.plan_path = (
            os.path.join(manager.directory, PLAN_FILENAME)
            if plan_path is None else os.fspath(plan_path)
        )
        self._initial_plan = plan
        self._drain = drain
        self._sleep = sleep
        self.base: Any = None
        self.engine: Any = None
        self._plan: plan_lib.TunedPlan | None = None
        self._pending_plan: plan_lib.TunedPlan | None = None
        self._armed_step: int | None = None
        self._drift_hits = 0
        self._last_check_step: int | None = None
        self._last_event_step: int | None = None
        #: chronological fleet events ({'event', 'step', 'detail'})
        self.events: list[dict[str, Any]] = []
        #: headline counters/timings (bench.py's _fleet_probe reads these)
        self.stats: dict[str, Any] = {
            'retunes': 0, 'migrations': 0, 'aborts': 0,
            'retune_s': None, 'migration_s': None, 'downtime_steps': None,
        }

    # ---------------------------------------------------------------- attach

    @property
    def plan(self) -> plan_lib.TunedPlan | None:
        """The plan the live engine is running under (None: canonical)."""
        return self._plan

    def attach(self, base: Any) -> Any:
        """Resolve the engine for ``base`` (a bare
        :class:`~kfac_tpu.KFACPreconditioner` config) under the best
        available plan.

        A plan whose fingerprint matches the live topology applies
        as-is; a stale or missing plan triggers the cost-model-only
        retune (the fingerprint mismatch is the "restored onto a changed
        topology" signal — topology is part of the fingerprint). Returns
        the built engine and binds it to the checkpoint manager.
        """
        if hasattr(base, 'mesh'):
            raise ValueError(
                'FleetController.attach takes the bare KFACPreconditioner '
                'config, not a built engine — the fleet must be free to '
                'pick the mesh'
            )
        self.base = base
        plan: plan_lib.TunedPlan | None = None
        source = self._initial_plan
        if source is None and os.path.exists(self.plan_path):
            source = self.plan_path
        if source is not None:
            try:
                plan = plan_lib.as_plan(source)
            except (TypeError, ValueError, OSError) as exc:
                warnings_lib.warn_fleet_event(
                    'plan-unreadable',
                    f'{type(exc).__name__}: {exc}; retuning from scratch',
                )
                plan = None
        current = plan_lib.plan_fingerprint(base.registry)
        if plan is not None and not plan_lib.fingerprint_matches(
            plan.fingerprint, current
        ):
            diff = plan_lib.fingerprint_diff(plan.fingerprint, current)
            warnings_lib.warn_fleet_event(
                'topology-changed',
                f'plan fingerprint differs on {"/".join(diff) or "?"}; '
                'running the cost-model-only retune',
            )
            plan = self._retune('topology-changed')
        elif plan is not None and not self._topology_fits(plan):
            topo = plan.knobs.get('topology') or {}
            warnings_lib.warn_fleet_event(
                'topology-changed',
                f"plan pipeline factorization pp={topo.get('pp')} "
                f"tp={topo.get('tp')} does not divide the "
                f'{jax.device_count()}-device world; running the '
                'cost-model-only retune',
            )
            plan = self._retune('topology-changed')
        elif plan is None:
            plan = self._retune('startup')
        engine, applied = self._build_engine(plan)
        self._plan = plan if applied else None
        self.engine = engine
        self.manager.engine = engine
        if self._plan is not None:
            self._persist(self._plan)
        return engine

    # ---------------------------------------------------------------- retune

    def _retune(self, reason: str) -> plan_lib.TunedPlan | None:
        """Cost-model-only fast path: rank the candidate grid with the
        analytic model (no measured trials, no engines built) under
        retry/backoff. Returns None after exhausting retries."""
        if self.base is None:
            raise ValueError('FleetController is not attached to a config')
        cfg = self.config
        t0 = time.monotonic()
        for attempt in range(cfg.retune_max_retries + 1):
            try:
                plan = search_lib.autotune(
                    self.base,
                    measure=False,
                    world=jax.device_count(),
                    hardware=self.hardware,
                    **self.search_overrides,
                )
                break
            except KeyboardInterrupt:
                raise
            except Exception as exc:
                if attempt == cfg.retune_max_retries:
                    warnings_lib.warn_fleet_event(
                        'retune-failed',
                        f'{type(exc).__name__}: {exc}; the canonical '
                        'layout stands',
                    )
                    self._event('retune-failed', detail=str(exc))
                    return None
                self._sleep(min(
                    cfg.retune_backoff_max,
                    cfg.retune_backoff_base * (2 ** attempt),
                ))
        plan.meta['retune_reason'] = reason
        plan.meta['fleet'] = True
        self.stats['retunes'] += 1
        self.stats['retune_s'] = time.monotonic() - t0
        self._event('retune', detail=reason)
        return plan

    @staticmethod
    def _topology_fits(plan: plan_lib.TunedPlan) -> bool:
        """A 3D-planner plan fits only when its ``pp * tp`` factors the
        live device count — an elastic shrink/grow can break that even
        when the coarse fingerprint still matches (same backend, same
        device kind, restored before the count is re-fingerprinted)."""
        topo = (plan.knobs or {}).get('topology')
        if not topo:
            return True
        pp = int(topo.get('pp', 1))
        tp = int(topo.get('tp', 1))
        return pp >= 1 and tp >= 1 and jax.device_count() % (pp * tp) == 0

    def _build_engine(
        self, plan: plan_lib.TunedPlan | None
    ) -> tuple[Any, bool]:
        """(engine, plan_applied). No controller state is mutated here —
        the migration path builds speculative engines it may discard."""
        from kfac_tpu.parallel.kaisa import DistributedKFAC

        if plan is None:
            return DistributedKFAC(config=self.base), False
        if (plan.knobs or {}).get('topology'):
            # topology plans drive pipeline engines (PipelinedLM /
            # PipelineKFAC own the pipe mesh); the flat KAISA engine
            # cannot honor them, so the fleet runs canonically
            warnings_lib.warn_fleet_event(
                'plan-not-applied',
                'plan carries a 3D topology; the fleet drives the flat '
                'KAISA engine, rebuilding under the canonical layout',
            )
            return DistributedKFAC(config=self.base), False
        engine = DistributedKFAC(config=self.base, auto_layout=plan)
        if not engine.auto_layout_applied:
            warnings_lib.warn_fleet_event(
                'plan-not-applied',
                'rebuilding under the canonical layout',
            )
            return DistributedKFAC(config=self.base), False
        return engine, True

    def _persist(self, plan: plan_lib.TunedPlan) -> None:
        if multihost.process_index() != 0:
            return
        try:
            plan.save(self.plan_path)
        except OSError as exc:
            warnings_lib.warn_fleet_event(
                'plan-persist-failed', f'{type(exc).__name__}: {exc}'
            )

    def _event(
        self, event: str, step: int | None = None, detail: str = ''
    ) -> None:
        self.events.append({'event': event, 'step': step, 'detail': detail})

    # --------------------------------------------------------------- restore

    def _has_committed(self) -> bool:
        return any(
            self.manager._is_committed(s)
            for s in self.manager.rotation_steps()
        )

    def restore_elastic(
        self, extra_template: dict[str, Any] | None = None
    ) -> manager_lib.RestoreResult | None:
        """Restore the newest good checkpoint into the tuned engine.

        The engine :meth:`attach` built already reflects the freshest
        plan for THIS topology, so the restore is elastic by
        construction (the rotation's manifests reshard the factors into
        the tuned layout). If the tuned restore fails while the rotation
        does hold committed checkpoints, the controller gracefully falls
        back: it rebuilds the canonical (plan-less) engine, restores
        into that, and rebinds. Returns None only on a genuinely empty
        or unrestorable rotation.
        """
        if self.engine is None:
            raise ValueError(
                'FleetController.restore_elastic before attach(): the '
                'Trainer calls attach for you, or call it explicitly'
            )
        result = None
        try:
            result = self.manager.restore_latest(
                engine=self.engine, extra_template=extra_template
            )
        except KeyboardInterrupt:
            raise
        except Exception as exc:
            warnings_lib.warn_fleet_event(
                'tuned-restore-failed',
                f'{type(exc).__name__}: {exc}; retrying under the '
                'canonical layout',
            )
        if result is not None:
            return result
        if not self._has_committed():
            return None  # fresh start, nothing to restore
        warnings_lib.warn_fleet_event(
            'tuned-restore-failed',
            'no rotation candidate restored under the tuned layout; '
            'retrying under the canonical layout',
        )
        engine, _ = self._build_engine(None)
        result = self.manager.restore_latest(
            engine=engine, extra_template=extra_template
        )
        if result is None:
            return None
        self._plan = None
        self.engine = engine
        self.manager.engine = engine
        self._event('restore-fallback', step=result.step)
        return result

    # ---------------------------------------------------------- steady state

    def on_step(self, trainer: Any, state: Any) -> Any:
        """Steady-state tick, called by the Trainer after each completed
        step (all four step paths). Returns the (possibly migrated)
        TrainState.

        SPMD symmetry: everything the decision depends on — the step
        cadence, the drained skew columns (already pod-aggregated), the
        deterministic cost model — is identical on every host, so every
        host arms and migrates on the same step; the explicit
        ``agree_decision`` vote then catches per-host *execution*
        failures (a bad filesystem, a failed reshard) rather than
        decision divergence.
        """
        cfg = self.config
        step = trainer._step_count
        if step is None:
            kstate = getattr(state, 'kfac_state', state)
            if kstate is None:
                return state
            step = int(jax.device_get(kstate.step))
        if self._pending_plan is not None:
            return self._maybe_migrate(trainer, state, step)
        if (
            self._last_event_step is not None
            and step - self._last_event_step < cfg.cooldown_steps
        ):
            return state
        if step % cfg.check_every != 0 or step == self._last_check_step:
            return state
        self._last_check_step = step
        drain = self._drain
        records = (
            drain(state) if drain is not None
            else flight_lib.drain_flight(state, skew_keys=cfg.drift_keys)
        )
        window = records[-cfg.drift_window:]
        if len(window) < cfg.drift_window:
            return state
        worst = max(
            sum(flight_lib.skew_ratio(rec, key) for rec in window)
            / len(window)
            for key in cfg.drift_keys
        )
        if worst <= cfg.drift_threshold:
            self._drift_hits = 0
            return state
        self._drift_hits += 1
        if self._drift_hits < cfg.drift_patience:
            return state
        self._drift_hits = 0
        self._event(
            'drift', step=step,
            detail=f'relative skew {worst:.3f} > {cfg.drift_threshold}',
        )
        if self.manager.save_interval_steps is None:
            warnings_lib.warn_fleet_event(
                'migration-disabled',
                'periodic saves are off — no checkpoint boundary to '
                'migrate at',
            )
            self._last_event_step = step
            return state
        plan = self._retune('drift')
        if plan is None:
            self._last_event_step = step
            return state
        if self._plan is not None and json.loads(
            json.dumps(plan.knobs)
        ) == json.loads(json.dumps(self._plan.knobs)):
            self._event('retune-noop', step=step,
                        detail='tuned knobs unchanged')
            self._last_event_step = step
            return state
        self._pending_plan = plan
        self._armed_step = step
        self._event('armed', step=step)
        return state

    def _maybe_migrate(self, trainer: Any, state: Any, step: int) -> Any:
        """Execute the armed migration once a checkpoint boundary
        arrives; mutate-nothing-until-verified (see module docstring)."""
        interval = self.manager.save_interval_steps
        if interval is None or step <= 0 or step % interval != 0:
            return state
        t0 = time.monotonic()
        ok = False
        result = None
        new_engine = None
        detail = ''
        try:
            # make this exact step durable first (idempotent when the
            # periodic save just committed it) — the rollback target
            self.manager.save_emergency(
                state, reason='fleet-migration', step=step
            )
            new_engine, applied = self._build_engine(self._pending_plan)
            if applied:
                _, template = manager_lib._split_train_state(state)
                result = self.manager.restore_latest(
                    engine=new_engine, extra_template=template
                )
                ok = result is not None and result.step == step
                if not ok:
                    detail = 'elastic restore failed or landed off-step'
            else:
                detail = 'pending plan did not apply'
        except KeyboardInterrupt:
            raise
        except Exception as exc:
            detail = f'{type(exc).__name__}: {exc}'
        ok = multihost.agree_decision(ok)
        pending, self._pending_plan = self._pending_plan, None
        armed_step, self._armed_step = self._armed_step, None
        self._last_event_step = step
        if not ok:
            self.stats['aborts'] += 1
            warnings_lib.warn_fleet_event(
                'migration-aborted',
                f'{detail or "a peer host failed"}; training continues '
                'on the last-good layout',
            )
            self._event('migration-aborted', step=step, detail=detail)
            return state
        self._plan = pending
        self.engine = new_engine
        self.manager.engine = new_engine
        self._persist(pending)
        new_state = state._replace(
            params=result.extra['params'],
            opt_state=result.extra['opt_state'],
            kfac_state=result.state,
            model_state=result.extra.get('model_state', state.model_state),
        )
        trainer.rebind_engine(new_engine)
        trainer.resume(new_state)
        self.stats['migrations'] += 1
        self.stats['migration_s'] = time.monotonic() - t0
        self.stats['downtime_steps'] = step - (
            armed_step if armed_step is not None else step
        )
        self._event(
            'migrated', step=step,
            detail=f'downtime {self.stats["downtime_steps"]} steps',
        )
        return new_state
