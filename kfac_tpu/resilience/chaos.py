"""Pod-scale chaos harness: preemption storms with measured recovery SLOs.

Every resilience ingredient in this repo ships — and is tested —
separately: signal-driven emergency saves (``signals.py`` +
``CheckpointManager.on_step``), rotation fallback across torn
checkpoints (``restore_latest``), elastic restore onto a changed
topology (``fleet._topology_fits``), drift→retune→vote→migrate
(``FleetController``), and real gloo CPU collectives across OS
processes (``tests/parallel/test_multihost.py``). This module composes
them under sustained adversarial pressure and measures how fast the
stack actually heals.

Architecture — one conductor, many victims:

* :class:`ChaosConductor` (parent process, never inside jax) owns the
  pod lifecycle: it spawns ``testing/chaos_worker.py`` OS processes
  that rendezvous through ``jax.distributed.initialize`` (the same
  KFAC_TPU_* env surface ``run_pod.sh`` exports per node), streams
  their per-rank JSONL event feeds, delivers scripted signal waves
  (SIGTERM / SIGUSR1) mid-run, corrupts the checkpoint rotation
  between runs (``testing/faults.py``), shrinks or grows the pod, and
  respawns. A storm is a sequence of such fault events
  (:func:`scripted_storm` grammar below); a seeded storm
  (:func:`seeded_storm`) draws events from ``random.Random(seed)``.

* The worker side (:func:`run_worker` / :func:`worker_recover`, called
  by ``testing/chaos_worker.py``) runs the REAL stack — Trainer +
  DistributedKFAC over the global gloo mesh + CheckpointManager, with
  an optional FleetController — and emits one JSON line per event
  (the ``resilience_worker.py`` convention). Its pod choreography is
  declared in :data:`CHAOS_RECOVERY_PROTOCOL` /
  :data:`CHAOS_STORM_PROTOCOL` so kfaclint's pod tier (KFL301–KFL305)
  bounded-model-checks it like the save and migration protocols.

* :class:`ChaosReport` reconciles the per-rank streams into
  per-fault-class SLO rows — downtime steps (work re-executed after
  the fault), recovery wall-clock (pod down → first post-restore step
  completed), restore fallback depth (rotation entries walked past),
  and trajectory divergence against an uninterrupted control run — and
  fails loudly (:class:`ChaosError`) when a configured budget is
  blown.

Storm schedule grammar (``ChaosConfig.schedule``) — a tuple of fault
events, each a dict:

* ``{'fault': 'sigterm_wave', 'ranks': (0, 2), 'at_step': 3}`` —
  deliver SIGTERM to the given ranks once any rank reports a step
  ``>= at_step``. One signalled rank downs the WHOLE pod cleanly: the
  flag propagates through ``agree_emergency``'s max-reduction, every
  rank lands the same emergency save and exits 0 (``Preempted``).
  The conductor then respawns the full pod, which resumes.
* ``{'fault': 'torn_checkpoint', 'ranks': (0,), 'at_step': 6}`` —
  SIGTERM wave as above, then tear the rotation while the pod is
  down: the ``LATEST`` pointer is truncated to garbage AND the newest
  step dir's payload is corrupted, so the respawned pod must walk
  back to the next committed rotation entry (fallback depth >= 1).
* ``{'fault': 'shrink', 'procs': 2, 'at_step': 9}`` (or ``'grow'``) —
  SIGTERM wave, then respawn with a different process count: the
  elastic-restore path (changed topology fingerprint; with a fleet, a
  retune onto the new world).
* ``{'fault': 'skew', 'ratio': 2.0, 'at_step': 6}`` — SIGTERM wave,
  then respawn with an injected flight-recorder skew
  (``testing.faults.skewed_drain``) so a fleet controller sees drift.
* ``{'fault': 'sigusr1', 'ranks': (1,), 'at_step': 10}`` — in-flight
  continue-signal: the pod snapshots at the agreed boundary and keeps
  training (no respawn).

Every event except ``sigusr1`` ends the current run; the pod's final
run (after the last schedule entry) trains to ``max_steps`` and exits
``done``. SLO rows attribute the recovery cost of transition ``k →
k+1`` to the fault event that caused it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal as signal_lib
import subprocess
import sys
import threading
import time
import warnings
from typing import Any, Callable

import jax

from kfac_tpu.parallel import multihost
from kfac_tpu.resilience.manager import Preempted
from kfac_tpu.warnings import CheckpointResilienceWarning

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_WORKER = os.path.join(REPO_ROOT, 'testing', 'chaos_worker.py')

#: committed SLO artifact (written by ``tools/kfac_chaos.py --out``):
#: the canonical scripted storm's reconciled report, folded read-only
#: into bench rounds by ``bench.py``'s ``_chaos_probe``
ARTIFACT_PATH = os.path.join(os.path.dirname(__file__), 'chaos_slo.json')


def load_slo_artifact(path: str = ARTIFACT_PATH) -> dict | None:
    """The committed chaos SLO artifact, or None when absent/unreadable.

    Read-only by design: bench rounds and docs tables fold the last
    MEASURED storm rather than re-running one (a storm spawns O(10) OS
    processes — minutes, not bench-probe seconds)."""
    try:
        with open(path) as f:
            artifact = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(artifact, dict) or 'rows' not in artifact:
        return None
    return artifact

#: Fault classes a storm can inject. ``sigusr1`` is the only in-flight
#: (non-pod-down) event; all others end the current run and attribute
#: the respawn's recovery cost to themselves.
FAULT_CLASSES = (
    'sigterm_wave', 'torn_checkpoint', 'corrupt_payload',
    'shrink', 'grow', 'skew', 'sigusr1',
)

#: Pod-down fault classes (everything except the in-flight snapshot).
_DOWN_FAULTS = tuple(f for f in FAULT_CLASSES if f != 'sigusr1')


# ------------------------------------------------------------- protocols
#
# The worker-side choreography, declared for kfaclint's pod tier
# (KFL305 model-checks the tables; its crosscheck asserts the named
# functions still reach ops of the declared kinds — delete the real
# barrier and the lint rots, not just this prose).

CHAOS_RECOVERY_PROTOCOL = {
    'machine': 'sequence',
    'name': 'chaos-recovery',
    'function': 'worker_recover',
    'steps': (
        # every (re)spawned rank rendezvouses before touching the
        # rotation: a fast rank must not race a peer still in jax
        # bring-up into a restore of different vintage
        {'op': 'rendezvous', 'rank': 'all', 'kind': 'barrier'},
        # newest-committed walk over the (possibly torn) rotation;
        # pure reads — mutation is SAVE_PROTOCOL's business
        {'op': 'restore_walk', 'rank': 'all', 'kind': 'host'},
        # unanimous vote that every rank's walk succeeded: a rank that
        # restored garbage must down the whole pod, not train alone
        {'op': 'agree_outcome', 'rank': 'all', 'kind': 'vote'},
        # all ranks verify they restored the SAME step before stepping
        {'op': 'align_step', 'rank': 'all', 'kind': 'collective'},
    ),
}

CHAOS_STORM_PROTOCOL = {
    'machine': 'state',
    'name': 'chaos-storm-worker',
    'function': 'run_worker',
    'vote_op': 'agree_decision',
    'states': ('down', 'recovering', 'running', 'storm', 'quiesced'),
    'initial': 'down',
    'transitions': (
        # conductor respawns the pod; each rank enters recovery
        {'from': 'down', 'event': 'spawn', 'to': 'recovering',
         'mutates': ()},
        # pod-unanimous restore agreement (reads only: the restore
        # mutates nothing durable — SAVE_PROTOCOL owns disk mutation)
        {'from': 'recovering', 'event': 'vote-commit', 'to': 'running',
         'mutates': ()},
        {'from': 'recovering', 'event': 'vote-abort', 'to': 'down',
         'mutates': ()},
        # a signal on ANY rank storms the whole pod via the
        # agree_emergency max-reduction at the next boundary
        {'from': 'running', 'event': 'preempt-signal', 'to': 'storm',
         'mutates': ()},
        {'from': 'storm', 'event': 'checkpoint-boundary', 'to': 'quiesced',
         'mutates': ()},
        # exit-semantics signal (SIGTERM): unwind, conductor respawns
        {'from': 'quiesced', 'event': 'exit', 'to': 'down',
         'mutates': ()},
        # continue-semantics signal (SIGUSR1): snapshot taken, train on
        {'from': 'quiesced', 'event': 'continue', 'to': 'running',
         'mutates': ()},
    ),
}


class ChaosError(AssertionError):
    """A blown SLO budget, a worker that died uncleanly, or a pod that
    wedged past its phase timeout. Inherits AssertionError so pytest
    renders the report verbatim."""


# ---------------------------------------------------------------- config


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Storm shape, fault mix, and SLO budgets (KFL111 pins the knob
    table in docs/ROBUSTNESS.md to these fields).

    Args:
        procs: initial pod size (OS processes; gloo ranks).
        devices_per_proc: virtual CPU devices per process — the global
            mesh spans ``procs * devices_per_proc`` devices.
        max_steps: steps the trajectory trains to (across all runs).
        save_interval: checkpoint cadence in steps; also bounds the
            work a clean preemption can lose.
        keep: rotation depth — must cover the deepest fallback a storm
            can force (torn newest entry -> at least 2).
        schedule: scripted storm, a tuple of fault-event dicts (module
            docstring grammar). Empty with ``seed=None`` selects
            :func:`scripted_storm`'s canonical small storm.
        seed: draw a random storm from :func:`seeded_storm` with this
            seed instead of using ``schedule`` (None: scripted).
        storm_events: pod-down events in a seeded storm.
        fault_mix: fault classes a seeded storm draws from.
        use_fleet: wrap the worker's engine in a FleetController (the
            elastic-restore + retune/migration paths; slower).
        step_sleep_s: per-step worker sleep so signal delivery lands
            mid-run deterministically on a loaded host.
        budget_downtime_steps: max steps of re-executed work per
            pod-down event before the report fails.
        budget_recovery_s: max pod-down -> first-post-restore-step
            wall-clock per event (CPU-container scale, includes
            process spawn + jax bring-up + rendezvous + compile).
        budget_fallback_depth: max rotation entries a restore may walk
            past (non-torn faults must not fall back at all).
        divergence_atol: max |storm loss - control loss| at equal step
            for same-world runs (0.0: bit-identical replay).
        elastic_divergence_rtol: relative loss tolerance after a
            shrink/grow (changed world re-lays-out reductions; exact
            bit equality is not defined across topologies).
        phase_timeout_s: per-run wall-clock limit before the conductor
            kills the pod and raises (a wedged rendezvous must not
            hang the suite).
    """

    procs: int = 4
    devices_per_proc: int = 1
    max_steps: int = 12
    save_interval: int = 2
    keep: int = 3
    schedule: tuple = ()
    seed: int | None = None
    storm_events: int = 3
    fault_mix: tuple = (
        'sigterm_wave', 'torn_checkpoint', 'corrupt_payload', 'shrink',
        'sigusr1',
    )
    use_fleet: bool = False
    step_sleep_s: float = 0.05
    budget_downtime_steps: int = 6
    budget_recovery_s: float = 600.0
    budget_fallback_depth: int = 1
    divergence_atol: float = 0.0
    elastic_divergence_rtol: float = 1e-4
    phase_timeout_s: float = 600.0

    def __post_init__(self) -> None:
        if self.procs < 2:
            raise ValueError(f'procs must be >= 2, got {self.procs}')
        if self.devices_per_proc < 1:
            raise ValueError(
                f'devices_per_proc must be >= 1, got '
                f'{self.devices_per_proc}'
            )
        if self.max_steps < 1:
            raise ValueError(f'max_steps must be >= 1, got {self.max_steps}')
        if self.save_interval < 1:
            raise ValueError(
                f'save_interval must be >= 1, got {self.save_interval}'
            )
        if self.keep < 2:
            raise ValueError(
                f'keep must be >= 2 (torn-checkpoint storms walk back '
                f'one rotation entry), got {self.keep}'
            )
        if self.schedule and self.seed is not None:
            raise ValueError(
                'pass schedule= (scripted) or seed= (random), not both'
            )
        unknown = {
            e.get('fault') for e in self.schedule
        } - set(FAULT_CLASSES)
        if unknown:
            raise ValueError(
                f'unknown fault class(es) {sorted(map(str, unknown))}; '
                f'expected a subset of {FAULT_CLASSES}'
            )
        bad_mix = set(self.fault_mix) - set(FAULT_CLASSES)
        if bad_mix:
            raise ValueError(
                f'unknown fault_mix class(es) {sorted(bad_mix)}; '
                f'expected a subset of {FAULT_CLASSES}'
            )


def resolve_schedule(config: ChaosConfig) -> tuple:
    """The storm the config describes: explicit schedule, seeded draw,
    or the canonical scripted small storm."""
    if config.schedule:
        return tuple(config.schedule)
    if config.seed is not None:
        return seeded_storm(config)
    return scripted_storm(config)


def scripted_storm(config: ChaosConfig) -> tuple:
    """The canonical deterministic small storm: one clean SIGTERM wave,
    one torn checkpoint, one topology shrink, one in-flight SIGUSR1
    snapshot — the three committed SLO fault classes plus the
    continue-signal path, sized to ``max_steps``."""
    s = config.save_interval
    kill1 = max(s + 1, config.max_steps // 4)
    kill2 = min(config.max_steps - 3, max(kill1 + s, config.max_steps // 2))
    # leave >= 2 steps of final-run headroom: a wave at max_steps - 1
    # races the pod's own completion, and a shrink that lands after
    # `done` measures an empty run instead of an elastic resume
    kill3 = min(config.max_steps - 2, kill2 + s)
    return (
        {'fault': 'sigterm_wave', 'ranks': (0, config.procs - 1),
         'at_step': kill1},
        {'fault': 'torn_checkpoint', 'ranks': (0,), 'at_step': kill2},
        {'fault': 'shrink', 'procs': max(2, config.procs // 2),
         'at_step': kill3},
        {'fault': 'sigusr1', 'ranks': (min(1, config.procs - 1),),
         'at_step': kill3},
    )


def seeded_storm(config: ChaosConfig) -> tuple:
    """Draw ``storm_events`` pod-down events (plus possible sigusr1
    snapshots) from ``random.Random(seed)``. Deterministic per seed."""
    rng = random.Random(config.seed)
    events: list[dict] = []
    procs = config.procs
    # kill points spread across the trajectory, always leaving room for
    # the final run to make progress
    lo, hi = config.save_interval + 1, max(
        config.save_interval + 2, config.max_steps - 2
    )
    downs = sorted(
        rng.randint(lo, hi) for _ in range(config.storm_events)
    )
    down_mix = [f for f in config.fault_mix if f != 'sigusr1']
    for at in downs:
        fault = rng.choice(down_mix) if down_mix else 'sigterm_wave'
        n_ranks = rng.randint(1, max(1, procs // 2))
        ranks = tuple(sorted(rng.sample(range(procs), n_ranks)))
        ev: dict[str, Any] = {'fault': fault, 'ranks': ranks, 'at_step': at}
        if fault == 'shrink':
            procs = max(2, procs // 2)
            ev['procs'] = procs
        elif fault == 'grow':
            procs = min(config.procs, procs * 2)
            ev['procs'] = procs
        elif fault == 'skew':
            ev['ratio'] = rng.choice((1.5, 2.0, 3.0))
        events.append(ev)
    if 'sigusr1' in config.fault_mix and rng.random() < 0.75:
        events.append({
            'fault': 'sigusr1',
            'ranks': (rng.randrange(procs),),
            'at_step': max(1, config.max_steps - 2),
        })
    return tuple(events)


# ------------------------------------------------------------ worker side
#
# Called from testing/chaos_worker.py inside each pod process. Keep the
# collective choreography branch-free and identical across ranks: the
# pod lint tier abstractly interprets this code over virtual ranks.


def worker_recover(trainer: Any, params: Any) -> tuple[Any, dict]:
    """Pod-coordinated restore — CHAOS_RECOVERY_PROTOCOL as code.

    Every rank: rendezvous barrier, walk the rotation for the newest
    committed checkpoint (counting fallback warnings), vote unanimously
    that the walk succeeded, then verify all ranks landed on the same
    step. Returns ``(state, meta)`` where meta carries the resumed
    step, fallback depth, and restore wall-clock."""
    multihost.barrier('kfac-chaos-recover')
    t0 = time.monotonic()
    err: Exception | None = None
    state = None
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter('always')
        try:
            state = trainer.restore_latest(params)
        except KeyboardInterrupt:
            raise
        except Exception as exc:  # noqa: BLE001 - vote on ANY failure
            err = exc
    resilience_warnings = [
        str(w.message) for w in caught
        if issubclass(w.category, CheckpointResilienceWarning)
    ]
    fallback_depth = sum(
        'falling back' in msg for msg in resilience_warnings
    )
    ok = multihost.agree_decision(err is None)
    if not ok:
        raise ChaosError(
            'pod-wide restore agreement failed '
            f'(this rank: {err!r}) — no rank may train alone on a '
            'divergent restore'
        ) from err
    if state is None:
        state = trainer.init(params)
    step = int(jax.device_get(state.kfac_state.step))
    multihost.assert_same_step(step, 'chaos recovery')
    return state, {
        'step': step,
        'fallback_depth': fallback_depth,
        'restore_s': time.monotonic() - t0,
        'warnings': resilience_warnings,
    }


def _fleet_stats(trainer: Any) -> dict | None:
    fleet = getattr(trainer, 'fleet', None)
    if fleet is None:
        return None
    return {
        'stats': dict(fleet.stats),
        'events': [dict(e) for e in fleet.events],
    }


def run_worker(
    trainer: Any,
    manager: Any,
    params: Any,
    make_batch: Callable[[Any], Any],
    max_steps: int,
    emit: Callable[..., None],
    step_sleep_s: float = 0.0,
) -> int:
    """One pod process's life inside the storm — CHAOS_STORM_PROTOCOL
    as code.

    Recover (pod-coordinated), then train to ``max_steps`` emitting one
    JSON line per step. A SIGTERM anywhere in the pod surfaces here as
    :class:`Preempted` after the coordinated emergency save — exit 0,
    the conductor respawns. ``make_batch(trainer)`` is called every
    step so the batch always lands on the CURRENT engine's mesh (a
    fleet migration can swap it mid-run)."""
    state, meta = worker_recover(trainer, params)
    emit(
        event='start',
        rank=multihost.process_index(),
        world=multihost.process_count(),
        resumed_step=meta['step'],
        fallback_depth=meta['fallback_depth'],
        restore_s=round(meta['restore_s'], 3),
        warnings=meta['warnings'],
    )
    loss = None
    try:
        for _ in range(meta['step'], max_steps):
            state, loss = trainer.step(state, make_batch(trainer))
            emit(
                event='step',
                step=int(jax.device_get(state.kfac_state.step)),
                loss=float(jax.device_get(loss)),
            )
            if step_sleep_s:
                time.sleep(step_sleep_s)
        manager.finalize()
        multihost.barrier('kfac-chaos-done')
        emit(
            event='done',
            final_step=int(jax.device_get(state.kfac_state.step)),
            latest=manager.latest_step(),
            rotation=manager.rotation_steps(),
            fleet=_fleet_stats(trainer),
        )
    except Preempted as exc:
        emit(
            event='preempted',
            signal=exc.signal_name,
            saved_step=exc.step,
            latest=manager.latest_step(),
            rotation=manager.rotation_steps(),
            fleet=_fleet_stats(trainer),
        )
    return 0


# --------------------------------------------------------------- conductor


@dataclasses.dataclass
class RunRecord:
    """One pod run between respawns, as observed by the conductor."""

    procs: int
    skew: float
    #: fault event that ended this run (None: ran to completion)
    down_event: dict | None
    #: (rank, t_monotonic, payload) in arrival order
    events: list = dataclasses.field(default_factory=list)
    t_launch: float = 0.0
    t_exit: float = 0.0
    t_kill: float | None = None
    returncodes: tuple = ()

    def per_rank(self, kind: str) -> dict[int, list[dict]]:
        out: dict[int, list[dict]] = {}
        for rank, _, payload in self.events:
            if payload.get('event') == kind:
                out.setdefault(rank, []).append(payload)
        return out

    def max_step(self) -> int:
        steps = [
            p['step'] for _, _, p in self.events
            if p.get('event') == 'step'
        ]
        return max(steps) if steps else 0

    def progress(self) -> int:
        """Furthest durable-or-observed step: a preemption unwinds from
        INSIDE the boundary step, so the emergency save can be one step
        past the last emitted step event."""
        saved = [
            p['saved_step'] for _, _, p in self.events
            if p.get('event') == 'preempted'
            and p.get('saved_step') is not None
        ]
        return max([self.max_step(), *saved])

    def losses(self) -> dict[int, dict[int, float]]:
        """rank -> {step: loss}."""
        out: dict[int, dict[int, float]] = {}
        for rank, _, p in self.events:
            if p.get('event') == 'step':
                out.setdefault(rank, {})[p['step']] = p['loss']
        return out

    def first_step_time(self) -> float | None:
        for _, t, p in self.events:
            if p.get('event') == 'step':
                return t
        return None


class ChaosConductor:
    """Owns the pod: spawn, signal, corrupt, respawn, measure.

    ``root`` holds the storm rotation (``<root>/storm``), the control
    rotation (``<root>/control``), per-rank stderr files, and the
    worker config JSON. The conductor itself never imports the worker's
    jax world — all coupling is argv + env + JSONL, exactly like a real
    pod scheduler."""

    def __init__(
        self,
        config: ChaosConfig,
        root: str,
        worker: str | None = None,
    ) -> None:
        self.config = config
        self.root = os.fspath(root)
        self.worker = worker or DEFAULT_WORKER
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------- pod ops

    def _worker_env(self, n: int, pid: int, port: int) -> dict:
        env = dict(os.environ)
        env['PALLAS_AXON_POOL_IPS'] = ''  # never touch the TPU tunnel
        env['JAX_PLATFORMS'] = 'cpu'
        flags = ' '.join(
            f for f in env.get('XLA_FLAGS', '').split()
            if 'xla_force_host_platform_device_count' not in f
        )
        env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count='
            f'{self.config.devices_per_proc}'
        ).strip()
        env['KFAC_TPU_COORDINATOR'] = f'127.0.0.1:{port}'
        env['KFAC_TPU_NUM_PROCESSES'] = str(n)
        env['KFAC_TPU_PROCESS_ID'] = str(pid)
        # all pod members share the repo's persistent compile cache:
        # n concurrent cold compiles contending for one core would
        # push the rendezvous past its timeout
        env.setdefault(
            'JAX_COMPILATION_CACHE_DIR',
            os.path.join(REPO_ROOT, '.jax_cache'),
        )
        return env

    def _spawn_pod(
        self, tag: str, ckpt_dir: str, n: int, skew: float, port: int
    ) -> list[subprocess.Popen]:
        cfg_path = os.path.join(self.root, f'worker_{tag}.json')
        with open(cfg_path, 'w') as f:
            json.dump({
                'ckpt_dir': ckpt_dir,
                'max_steps': self.config.max_steps,
                'save_interval': self.config.save_interval,
                'keep': self.config.keep,
                'step_sleep_s': self.config.step_sleep_s,
                'use_fleet': self.config.use_fleet,
                'skew': skew,
            }, f)
        procs = []
        for pid in range(n):
            stderr = open(  # noqa: SIM115 - lives past this scope
                os.path.join(self.root, f'stderr_{tag}_r{pid}.log'), 'w'
            )
            procs.append(subprocess.Popen(
                [sys.executable, self.worker, cfg_path],
                env=self._worker_env(n, pid, port),
                cwd=REPO_ROOT,
                stdout=subprocess.PIPE,
                stderr=stderr,
                text=True,
            ))
        return procs

    def _stderr_tails(self, tag: str, n: int) -> str:
        tails = []
        for pid in range(n):
            path = os.path.join(self.root, f'stderr_{tag}_r{pid}.log')
            try:
                with open(path) as f:
                    tail = f.read()[-1500:]
            except OSError:
                tail = '<unreadable>'
            tails.append(f'--- rank {pid} stderr ---\n{tail}')
        return '\n'.join(tails)

    def _run_pod(
        self,
        tag: str,
        ckpt_dir: str,
        n: int,
        skew: float,
        down_event: dict | None,
        snapshots: tuple = (),
    ) -> RunRecord:
        """One pod run: spawn n ranks, stream events, deliver scripted
        signals, collect. Raises ChaosError on unclean exits or a
        wedged pod."""
        import socket

        with socket.socket() as s:
            s.bind(('127.0.0.1', 0))
            port = s.getsockname()[1]
        rec = RunRecord(procs=n, skew=skew, down_event=down_event)
        rec.t_launch = time.monotonic()
        procs = self._spawn_pod(tag, ckpt_dir, n, skew, port)
        lock = threading.Lock()
        kill_trigger = threading.Event()
        snap_triggers = [threading.Event() for _ in snapshots]
        kill_at = down_event.get('at_step') if down_event else None

        def _reader(rank: int, proc: subprocess.Popen) -> None:
            for line in proc.stdout:
                line = line.strip()
                if not line.startswith('{'):
                    continue
                try:
                    payload = json.loads(line)
                except ValueError:
                    continue
                with lock:
                    rec.events.append((rank, time.monotonic(), payload))
                if payload.get('event') != 'step':
                    continue
                step = payload.get('step', 0)
                if kill_at is not None and step >= kill_at:
                    kill_trigger.set()
                for snap, trig in zip(snapshots, snap_triggers):
                    if step >= snap.get('at_step', 0):
                        trig.set()

        threads = [
            threading.Thread(target=_reader, args=(i, p), daemon=True)
            for i, p in enumerate(procs)
        ]
        for t in threads:
            t.start()

        deadline = time.monotonic() + self.config.phase_timeout_s
        try:
            delivered_snaps = [False] * len(snapshots)
            killed = False
            while True:
                alive = [p for p in procs if p.poll() is None]
                for i, (snap, trig) in enumerate(
                    zip(snapshots, snap_triggers)
                ):
                    if trig.is_set() and not delivered_snaps[i]:
                        delivered_snaps[i] = True
                        self._signal(procs, snap.get('ranks', (0,)),
                                     signal_lib.SIGUSR1)
                if kill_trigger.is_set() and not killed:
                    killed = True
                    rec.t_kill = time.monotonic()
                    self._signal(
                        procs,
                        down_event.get('ranks', (0,)),
                        signal_lib.SIGTERM,
                    )
                if not alive:
                    break
                if time.monotonic() > deadline:
                    for p in procs:
                        p.kill()
                    raise ChaosError(
                        f'chaos pod {tag!r} wedged past '
                        f'{self.config.phase_timeout_s:.0f}s '
                        f'(killed={killed}, events={len(rec.events)}):\n'
                        + self._stderr_tails(tag, n)
                    )
                time.sleep(0.02)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for p in procs:
                p.wait()
            for t in threads:
                t.join(timeout=10)
        rec.t_exit = time.monotonic()
        rec.returncodes = tuple(p.returncode for p in procs)
        if any(rc != 0 for rc in rec.returncodes):
            raise ChaosError(
                f'chaos pod {tag!r} exited uncleanly '
                f'(returncodes={rec.returncodes}) — a preempted worker '
                'must save and exit 0:\n' + self._stderr_tails(tag, n)
            )
        return rec

    @staticmethod
    def _signal(procs, ranks, sig) -> None:
        for rank in ranks:
            if 0 <= rank < len(procs) and procs[rank].poll() is None:
                procs[rank].send_signal(sig)

    # ------------------------------------------------------------- faults

    def _apply_disk_fault(self, ckpt_dir: str, fault: str) -> list[str]:
        """Corrupt the rotation while the pod is down. Returns the
        victim paths (for the report)."""
        # lazy import: testing/ is the dev-harness package; the library
        # proper must stay importable without it
        from testing import faults

        victims = []
        if fault == 'torn_checkpoint':
            victims.append(faults.corrupt_checkpoint(ckpt_dir, 'torn_latest'))
            newest = self._newest_step_dir(ckpt_dir)
            if newest is not None:
                victims.append(faults.corrupt_checkpoint(newest, 'truncate'))
        elif fault == 'corrupt_payload':
            newest = self._newest_step_dir(ckpt_dir)
            if newest is None:
                raise ChaosError(
                    'corrupt_payload scheduled but the rotation at '
                    f'{ckpt_dir!r} holds no step dir'
                )
            victims.append(faults.corrupt_checkpoint(newest, 'truncate'))
        return [str(v) for v in victims]

    @staticmethod
    def _newest_step_dir(ckpt_dir: str) -> str | None:
        steps = []
        try:
            entries = os.listdir(ckpt_dir)
        except FileNotFoundError:
            return None
        for name in entries:
            if name.startswith('step_'):
                try:
                    steps.append((int(name[len('step_'):]), name))
                except ValueError:
                    continue
        if not steps:
            return None
        return os.path.join(ckpt_dir, max(steps)[1])

    # --------------------------------------------------------------- storm

    def run(self) -> 'ChaosReport':
        """Drive the full storm plus the uninterrupted control run and
        reconcile. Raises :class:`ChaosError` when a budget is blown."""
        schedule = resolve_schedule(self.config)
        storm_dir = os.path.join(self.root, 'storm')
        control_dir = os.path.join(self.root, 'control')
        os.makedirs(storm_dir, exist_ok=True)
        os.makedirs(control_dir, exist_ok=True)

        # split the schedule into pod runs: each pod-down event ends a
        # run; sigusr1 events ride inside the run they precede
        runs: list[dict] = []
        pending_snaps: list[dict] = []
        for ev in schedule:
            if ev['fault'] == 'sigusr1':
                pending_snaps.append(ev)
            else:
                runs.append({'down': ev, 'snaps': tuple(pending_snaps)})
                pending_snaps = []
        runs.append({'down': None, 'snaps': tuple(pending_snaps)})

        records: list[RunRecord] = []
        faults_applied: list[dict] = []
        procs = self.config.procs
        skew = 0.0
        for k, run in enumerate(runs):
            rec = self._run_pod(
                f'storm{k}', storm_dir, procs, skew,
                run['down'], run['snaps'],
            )
            records.append(rec)
            down = run['down']
            if down is None:
                continue
            applied = {'fault': down['fault'], 'event': dict(down)}
            if down['fault'] in ('torn_checkpoint', 'corrupt_payload'):
                applied['victims'] = self._apply_disk_fault(
                    storm_dir, down['fault']
                )
            if down['fault'] in ('shrink', 'grow'):
                procs = int(down['procs'])
            if down['fault'] == 'skew':
                skew = float(down.get('ratio', 2.0))
            faults_applied.append(applied)

        control = self._run_pod(
            'control', control_dir, self.config.procs, 0.0, None, ()
        )
        report = reconcile(self.config, runs, records, control)
        report.faults_applied = faults_applied
        if report.blown:
            err = ChaosError(
                'chaos SLO budget blown:\n  - '
                + '\n  - '.join(report.blown)
                + '\n' + json.dumps(report.rows, indent=1, sort_keys=True)
            )
            err.report = report
            raise err
        return report


# ---------------------------------------------------------------- report


@dataclasses.dataclass
class ChaosReport:
    """Reconciled storm outcome: per-fault-class SLO rows plus the
    blown-budget list (empty = all SLOs met)."""

    config: dict
    schedule: tuple
    rows: dict
    runs: list
    blown: list
    faults_applied: list = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.blown

    def to_json(self) -> dict:
        return {
            'config': self.config,
            'schedule': list(self.schedule),
            'rows': self.rows,
            'runs': self.runs,
            'blown': list(self.blown),
            'faults_applied': self.faults_applied,
            'ok': self.ok,
        }


def reconcile(
    config: ChaosConfig,
    runs: list[dict],
    records: list[RunRecord],
    control: RunRecord,
) -> ChaosReport:
    """Fold the per-rank event streams into SLO rows.

    Per pod-down event (the ``k -> k+1`` respawn transition):

    * ``downtime_steps`` — work re-executed: the highest step the dying
      pod reached minus the step the respawned pod resumed from.
    * ``recovery_s`` — wall-clock from the dying pod fully exiting to
      the respawned pod completing its first step (spawn + jax
      bring-up + rendezvous + restore + compile).
    * ``fallback_depth`` — max rotation entries any rank's restore
      walked past.
    * divergence — every storm step's loss is compared to the control
      run at the same step: bit-identical (``divergence_atol``) for
      same-world runs, ``elastic_divergence_rtol`` after shrink/grow.
    """
    blown: list[str] = []
    control_losses = _merged_losses(control, blown, 'control')

    rows: dict[str, dict] = {}
    run_summaries: list[dict] = []
    for k, (run, rec) in enumerate(zip(runs, records)):
        starts = rec.per_rank('start')
        resumed = {r: evs[0]['resumed_step'] for r, evs in starts.items()}
        fallback = {r: evs[0]['fallback_depth'] for r, evs in starts.items()}
        if len(set(resumed.values())) > 1:
            blown.append(
                f'run {k}: ranks resumed from different steps {resumed} '
                '(assert_same_step should have caught this)'
            )
        losses = _merged_losses(rec, blown, f'run {k}')
        same_world = rec.procs == control.procs and rec.skew == 0.0
        div = _divergence(losses, control_losses)
        if div is not None:
            limit_kind = 'atol' if same_world else 'rtol'
            limit = (
                config.divergence_atol if same_world
                else config.elastic_divergence_rtol
            )
            value = div['abs'] if same_world else div['rel']
            if value > limit:
                blown.append(
                    f'run {k}: trajectory diverged from control '
                    f'({limit_kind} {value:.3e} > {limit:.3e} at step '
                    f'{div["step"]})'
                )
        run_summaries.append({
            'run': k,
            'procs': rec.procs,
            'skew': rec.skew,
            'fault': run['down']['fault'] if run['down'] else None,
            'resumed_step': min(resumed.values()) if resumed else None,
            'max_step': rec.max_step(),
            'fallback_depth': max(fallback.values()) if fallback else 0,
            'steps_observed': len(losses),
            'divergence': div,
            'world_changed': not same_world,
            'restore_warnings': sorted({
                w for evs in starts.values()
                for w in evs[0].get('warnings', ())
            }),
        })

        # SLO row for the fault that ended the PREVIOUS run
        if k == 0:
            continue
        prev, prev_rec = runs[k - 1], records[k - 1]
        down = prev['down']
        if down is None:
            continue
        fault = down['fault']
        first_step_t = rec.first_step_time()
        recovery_s = (
            first_step_t - prev_rec.t_exit
            if first_step_t is not None else None
        )
        resumed_step = min(resumed.values()) if resumed else 0
        downtime = prev_rec.progress() - resumed_step
        depth = max(fallback.values()) if fallback else 0
        row = rows.setdefault(fault, {
            'events': 0, 'downtime_steps': 0, 'recovery_s': 0.0,
            'fallback_depth': 0, 'max_divergence': 0.0,
        })
        row['events'] += 1
        row['downtime_steps'] = max(row['downtime_steps'], downtime)
        if recovery_s is not None:
            row['recovery_s'] = round(
                max(row['recovery_s'], recovery_s), 3
            )
        row['fallback_depth'] = max(row['fallback_depth'], depth)
        if div is not None:
            row['max_divergence'] = max(row['max_divergence'], div['abs'])
        if downtime > config.budget_downtime_steps:
            blown.append(
                f'{fault}: downtime {downtime} steps > budget '
                f'{config.budget_downtime_steps}'
            )
        if downtime < 0:
            blown.append(
                f'{fault}: respawned pod resumed AHEAD of the dying '
                f'pod ({resumed_step} > {prev_rec.progress()}) — the '
                'rotation restored a future step'
            )
        if recovery_s is not None and (
            recovery_s > config.budget_recovery_s
        ):
            blown.append(
                f'{fault}: recovery {recovery_s:.1f}s > budget '
                f'{config.budget_recovery_s:.1f}s'
            )
        if depth > config.budget_fallback_depth:
            blown.append(
                f'{fault}: restore fell back {depth} rotation entries '
                f'> budget {config.budget_fallback_depth}'
            )
        if fault == 'torn_checkpoint' and depth < 1:
            blown.append(
                'torn_checkpoint: restore did not fall back at all — '
                'the injected corruption was never exercised'
            )

    # the trajectory must COMPLETE: final run reaches max_steps. A
    # fast pod can finish the trajectory before the last wave lands;
    # the respawned final run then restores AT max_steps and exits
    # done with zero step events — that resumed_step is completion,
    # not a stall.
    final = records[-1]
    final_resumed = [
        p['resumed_step'] for _, _, p in final.events
        if p.get('event') == 'start' and p.get('resumed_step') is not None
    ]
    final_progress = max([final.max_step(), *final_resumed], default=0)
    if final_progress < config.max_steps:
        blown.append(
            f'storm never completed: final run reached step '
            f'{final_progress} < max_steps {config.max_steps}'
        )
    if control.max_step() < config.max_steps:
        blown.append(
            f'control run reached step {control.max_step()} < '
            f'max_steps {config.max_steps}'
        )

    # in-flight snapshots: pod kept training (no respawn), so their SLO
    # row is just the event count + divergence already checked above
    for run, rec in zip(runs, records):
        for snap in run['snaps']:
            row = rows.setdefault('sigusr1', {
                'events': 0, 'downtime_steps': 0, 'recovery_s': 0.0,
                'fallback_depth': 0, 'max_divergence': 0.0,
            })
            row['events'] += 1

    return ChaosReport(
        config=dataclasses.asdict(config),
        schedule=tuple(
            dict(r['down']) for r in runs if r['down'] is not None
        ),
        rows=rows,
        runs=run_summaries,
        blown=blown,
    )


def _merged_losses(
    rec: RunRecord, blown: list[str], tag: str
) -> dict[int, float]:
    """Per-step losses, asserting all ranks agree bit-for-bit (the
    training math is replicated over the pod)."""
    per_rank = rec.losses()
    merged: dict[int, float] = {}
    for rank, losses in per_rank.items():
        for step, loss in losses.items():
            if step in merged and merged[step] != loss:
                blown.append(
                    f'{tag}: rank {rank} loss at step {step} '
                    f'({loss!r}) disagrees with a peer ({merged[step]!r})'
                )
            merged.setdefault(step, loss)
    return merged


def _divergence(
    losses: dict[int, float], control: dict[int, float]
) -> dict | None:
    """Worst |storm - control| over the overlapping steps."""
    common = sorted(set(losses) & set(control))
    if not common:
        return None
    worst = {'step': None, 'abs': 0.0, 'rel': 0.0}
    for step in common:
        a, b = losses[step], control[step]
        d = abs(a - b)
        rel = d / max(abs(b), 1e-30)
        if d >= worst['abs']:
            worst = {'step': step, 'abs': d, 'rel': rel}
    return worst
