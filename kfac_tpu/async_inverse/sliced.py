"""Sliced on-device async refresh: the window's eigh work, one slice per step.

Replaces the synchronous inverse-cadence ``lax.cond`` in the engines'
``step`` with a three-stage in-jit dispatcher:

1. **swap** (``phase == 0``): promote a complete, finite, non-quarantined
   shadow into the active slots, advance ``last_inv_step`` for the layers
   that actually swapped (staleness metrics stay truthful), update the
   health degradation counters, and reset slice progress.
2. **cold start** (``step == 0``): one synchronous ``update_inverses`` so
   the first window never preconditions with zero decompositions — same
   as the synchronous path's step-0 refresh.
3. **slice** (``lax.switch`` on the window phase): refresh this phase's
   unit bucket into the shadow from the CURRENT factors. Slices use the
   very same decomposition kernels as the synchronous path
   (``compute_eigh`` / ``damped_inverse`` / the distributed engine's
   sharded batched eigh), so a swapped shadow is bit-identical to what a
   synchronous refresh would have produced from the same factors — the
   active decompositions are simply one window staler.

Units are balanced across slices by the n^3 compute weighting
(:func:`kfac_tpu.assignment.compute_work_costs` heuristic): the dense
engine slices per (factor side, layer) — per layer when fused prediv ties
the sides together — and the distributed engine per storage bucket (per
pair bucket under prediv), so one size-class batched eigh runs per step.

Quarantine interaction (PR-1 sentinel): a layer quarantined at the
boundary has its in-flight shadow refresh DISCARDED, not swapped — the
factors that produced it were suspect. The degradation counter advances
through :func:`kfac_tpu.health.inversion_update` exactly as a quarantined
synchronous refresh would.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from kfac_tpu import enums
from kfac_tpu import health as health_lib
from kfac_tpu import tracing
from kfac_tpu.async_inverse import slots as slots_lib
from kfac_tpu.observability import metrics as metrics_lib
from kfac_tpu.ops import factors as factors_lib


def _resolve(value, step):
    if callable(value):
        return value(step)
    return value


def decomp_fields(compute_method, prediv: bool) -> tuple[str, ...]:
    """The engine-state decomposition fields a config actually uses."""
    if compute_method == enums.ComputeMethod.EIGEN:
        if prediv:
            return ('qa', 'qg', 'dgda')
        return ('qa', 'qg', 'da', 'dg')
    return ('a_inv', 'g_inv')


# --------------------------------------------------------------------- dense


def dense_units(engine) -> list[tuple[tuple[str, str], float]]:
    """Refresh units for the dense engine: ``((side, layer), n^3 cost)``.

    The two factor sides of a layer decompose independently, so they are
    separate units (halving the worst slice) — except under fused prediv,
    where ``dgda`` needs both sides' eigenvalues in one place.
    """
    units: list[tuple[tuple[str, str], float]] = []
    eigen = engine.compute_method == enums.ComputeMethod.EIGEN
    fused = eigen and engine.prediv_eigenvalues
    for name, h in engine.registry.layers.items():
        na = float(h.a_factor_shape[0]) ** 3
        ng = float(h.g_factor_shape[0]) ** 3
        if fused:
            units.append((('ag', name), na + ng))
        else:
            units.append((('a', name), na))
            units.append((('g', name), ng))
    return units


def dense_shadow(engine, state) -> slots_lib.ShadowSlots:
    """A zeroed shadow mirroring the dense state's decomposition slots."""
    fields = decomp_fields(engine.compute_method, engine.prediv_eigenvalues)
    return slots_lib.empty_shadow(
        {f: getattr(state, f) for f in fields}
    )


def dense_swap_core(engine, state, cand, complete):
    """Gated promotion of candidate decompositions into the active slots.

    ``cand`` maps field name -> {layer: array} (already in ``inv_dtype``);
    ``complete`` is a traced bool — False leaves everything untouched.
    Shared by the sliced swap (candidates from the shadow) and the host
    backend's apply (candidates from the worker payload, complete=True).

    Per layer, all fields swap together (no torn A/G mixtures), gated on
    finiteness (health only — the synchronous path doesn't finite-check
    either when the sentinel is off) and on the quarantine flag.
    ``last_inv_step`` advances only for layers that swapped.
    """
    cfg = engine.health
    h = state.health
    fields = decomp_fields(engine.compute_method, engine.prediv_eigenvalues)
    new = {f: dict(getattr(state, f)) for f in fields}
    bad_inv = dict(h.bad_inv) if cfg is not None else {}
    touched: dict[str, jax.Array] = {}
    for name in engine.registry.layers:
        if cfg is not None:
            ok = jnp.stack(
                [jnp.isfinite(cand[f][name]).all() for f in fields]
            ).all()
            swapped = complete & ok & (h.quarantined[name] <= 0)
            bad_inv[name] = jnp.where(
                complete,
                health_lib.inversion_update(
                    cfg, ok, h.quarantined[name], h.bad_inv[name]
                ),
                h.bad_inv[name],
            )
        else:
            swapped = jnp.asarray(complete)
        for f in fields:
            new[f][name] = jnp.where(
                swapped, cand[f][name], getattr(state, f)[name]
            )
        touched[name] = swapped
    state = state._replace(**new)
    if cfg is not None:
        state = state._replace(health=h._replace(bad_inv=bad_inv))
    if engine.metrics is not None and state.metrics is not None:
        ms = state.metrics
        state = state._replace(metrics=ms._replace(
            last_inv_step=metrics_lib.advance_last(
                ms.last_inv_step, ms.names, touched, state.step)))
    return state


def _dense_swap(engine, state):
    sh = state.shadow
    fields = decomp_fields(engine.compute_method, engine.prediv_eigenvalues)
    state = dense_swap_core(
        engine, state,
        {f: getattr(sh, f) for f in fields},
        sh.progress >= engine._async_n_slices,
    )
    # progress resets unconditionally: it counts slices since the last
    # boundary, and every unit is recomputed each window regardless of
    # whether this boundary's swap fired
    return state._replace(
        shadow=state.shadow._replace(progress=jnp.zeros((), jnp.int32))
    )


def _dense_slice(engine, state, units):
    """Refresh one slice's units into the shadow from CURRENT factors."""
    sh = state.shadow
    cfg = engine.health
    h = state.health
    damping = _resolve(engine.damping, state.step)
    eigen = engine.compute_method == enums.ComputeMethod.EIGEN
    fields = decomp_fields(engine.compute_method, engine.prediv_eigenvalues)
    upd = {f: dict(getattr(sh, f)) for f in fields}

    def eff(name):
        if cfg is None:
            return damping
        return damping * h.damping_mult[name]

    for side, name in units:
        if eigen:
            if side in ('a', 'ag'):
                adec = factors_lib.compute_eigh(
                    state.a[name], engine.inv_dtype, engine.eigh_impl
                )
                upd['qa'][name] = adec.q
                if not engine.prediv_eigenvalues:
                    upd['da'][name] = adec.d
            if side in ('g', 'ag'):
                gdec = factors_lib.compute_eigh(
                    state.g[name], engine.inv_dtype, engine.eigh_impl
                )
                upd['qg'][name] = gdec.q
                if not engine.prediv_eigenvalues:
                    upd['dg'][name] = gdec.d
            if side == 'ag':
                upd['dgda'][name] = factors_lib.prediv_eigenvalues(
                    adec, gdec, eff(name)
                ).astype(engine.inv_dtype)
        else:
            # warm-start from the ACTIVE inverse: the factor EMA drifts
            # slowly across a window, so it is deep in the quadratic basin
            # (same rationale as the synchronous path's warm start)
            if side == 'a':
                upd['a_inv'][name] = factors_lib.damped_inverse(
                    state.a[name], eff(name), engine.inv_dtype,
                    engine.inverse_solver, engine.newton_schulz_iters,
                    x0=state.a_inv[name],
                )
            else:
                upd['g_inv'][name] = factors_lib.damped_inverse(
                    state.g[name], eff(name), engine.inv_dtype,
                    engine.inverse_solver, engine.newton_schulz_iters,
                    x0=state.g_inv[name],
                )
    return state._replace(shadow=sh._replace(
        progress=sh.progress + 1,
        damping=jnp.asarray(damping, jnp.float32),
        **upd,
    ))


@tracing.scope('kfac.async_refresh')
def dense_async_step(engine, state):
    """The dense engine's in-jit async dispatcher (replaces the inverse
    cadence cond). See the module docstring for the three stages."""
    phase = jnp.mod(state.step, engine._async_n_steps)
    state = jax.lax.cond(
        phase == 0, partial(_dense_swap, engine), lambda s: s, state
    )
    state = jax.lax.cond(
        state.step == 0, engine.update_inverses, lambda s: s, state
    )
    n_slices = engine._async_n_slices
    branches = [
        partial(_dense_slice, engine, units=u) for u in engine._async_slices
    ] + [lambda s: s]
    return jax.lax.switch(jnp.minimum(phase, n_slices), branches, state)


# --------------------------------------------------------------- distributed


def kaisa_units(engine) -> list[tuple[tuple[str, str], float]]:
    """Refresh units for the distributed engine: one storage bucket's
    sharded batched decomposition per unit (``(side, bucket_key)``), or
    one pair bucket (``('ag', key)``) under fused prediv. Costs are the
    stack's total n^3 FLOPs — the padded slot count times the class dim
    cubed — matching what :meth:`_sharded_eigh` actually executes."""
    units: list[tuple[tuple[str, str], float]] = []
    if engine._prediv:
        for b in engine.buckets:
            units.append(
                (('ag', b.key), b.padded * (float(b.da) ** 3 + float(b.dg) ** 3))
            )
        return units
    for sb in engine.a_store:
        units.append((('a', sb.key), sb.padded * float(sb.d) ** 3))
    for sb in engine.g_store:
        units.append((('g', sb.key), sb.padded * float(sb.d) ** 3))
    return units


def kaisa_shadow(engine, state) -> slots_lib.ShadowSlots:
    """A zeroed shadow mirroring the stacked decomposition slots (shapes,
    dtypes, and — outside jit — shardings follow the active fields)."""
    fields = decomp_fields(engine.config.compute_method, engine._prediv)
    return slots_lib.empty_shadow(
        {f: getattr(state, f) for f in fields}
    )


def kaisa_swap_core(engine, state, cand, cand_damping, complete):
    """Stacked-layout swap: per-layer gates scattered onto per-slot masks.

    A layer's A and G slots (possibly in different stacks under
    ``colocate_factors=False``) swap together or not at all — the
    per-layer verdict (finite on every field, not quarantined) is
    scattered into each storage bucket's ``(L,)`` mask with the same
    update-slice assembly as ``_slot_mask`` (GSPMD stack hazard).
    ``inv_damping`` is promoted to the damping the candidates were built
    at. Shared by the sliced swap and the host backend's apply.
    """
    from jax.sharding import NamedSharding

    cfg = engine.config
    hc = cfg.health
    h = state.health
    dec = NamedSharding(engine.mesh, engine._decomp_spec())
    eigen = engine._eigen
    prediv = engine._prediv

    def slot_finite(arrays):
        ok = jnp.isfinite(arrays[0]).all(
            axis=tuple(range(1, arrays[0].ndim))
        )
        for x in arrays[1:]:
            ok = ok & jnp.isfinite(x).all(axis=tuple(range(1, x.ndim)))
        return ok

    bad_inv = dict(h.bad_inv) if hc is not None else {}
    touched: dict[str, jax.Array] = {}
    if hc is not None:
        # per-slot finite verdicts per store, then combined per layer
        ok_a = {
            sb.key: slot_finite(
                [cand['qa'][sb.key]]
                + ([cand['da'][sb.key]] if eigen and not prediv else [])
                if eigen else [cand['a_inv'][sb.key]]
            )
            for sb in engine.a_store
        }
        ok_g = {
            sb.key: slot_finite(
                [cand['qg'][sb.key]]
                + ([cand['dg'][sb.key]] if eigen and not prediv else [])
                if eigen else [cand['g_inv'][sb.key]]
            )
            for sb in engine.g_store
        }
        ok_fused = (
            {b.key: slot_finite([cand['dgda'][b.key]]) for b in engine.buckets}
            if prediv else {}
        )
        swap_flags: dict[str, jax.Array] = {}
        for n in engine.registry.layers:
            ak, ai = engine._a_slot[n]
            gk, gi = engine._g_slot[n]
            okn = ok_a[ak][ai] & ok_g[gk][gi]
            if prediv:
                okn = okn & ok_fused[ak][ai]
            swapped = complete & okn & (h.quarantined[n] <= 0)
            swap_flags[n] = swapped
            touched[n] = swapped
            bad_inv[n] = jnp.where(
                complete,
                health_lib.inversion_update(
                    hc, okn, h.quarantined[n], h.bad_inv[n]
                ),
                h.bad_inv[n],
            )

        def store_mask(layers, padded):
            return engine._slot_mask(swap_flags, layers, padded)
    else:
        for n in engine.registry.layers:
            touched[n] = jnp.asarray(complete)

    def swap_stack(store, field):
        out = {}
        for sb in store:
            active = getattr(state, field)[sb.key]
            c = cand[field][sb.key]
            if hc is None:
                gate = jnp.asarray(complete)
            else:
                gate = store_mask(sb.layers, sb.padded)
            shaped = gate.reshape(gate.shape + (1,) * (c.ndim - gate.ndim))
            out[sb.key] = jax.lax.with_sharding_constraint(
                jnp.where(shaped, c, active), dec
            )
        return out

    if eigen:
        upd = {
            'qa': swap_stack(engine.a_store, 'qa'),
            'qg': swap_stack(engine.g_store, 'qg'),
        }
        if prediv:
            upd['dgda'] = swap_stack(engine.buckets, 'dgda')
        else:
            upd['da'] = swap_stack(engine.a_store, 'da')
            upd['dg'] = swap_stack(engine.g_store, 'dg')
    else:
        upd = {
            'a_inv': swap_stack(engine.a_store, 'a_inv'),
            'g_inv': swap_stack(engine.g_store, 'g_inv'),
        }
    state = state._replace(
        **upd,
        inv_damping=jnp.where(complete, cand_damping, state.inv_damping),
    )
    if hc is not None:
        state = state._replace(health=h._replace(bad_inv=bad_inv))
    if cfg.metrics is not None and state.metrics is not None:
        ms = state.metrics
        state = state._replace(metrics=ms._replace(
            last_inv_step=metrics_lib.advance_last(
                ms.last_inv_step, ms.names, touched, state.step)))
    return state


def _kaisa_swap(engine, state):
    sh = state.shadow
    fields = decomp_fields(engine.config.compute_method, engine._prediv)
    state = kaisa_swap_core(
        engine, state,
        {f: getattr(sh, f) for f in fields},
        sh.damping,
        sh.progress >= engine._async_n_slices,
    )
    return state._replace(
        shadow=state.shadow._replace(progress=jnp.zeros((), jnp.int32))
    )


def _kaisa_slice(engine, state, units):
    """Refresh one slice's storage buckets into the stacked shadow.

    Same kernels and shardings as the synchronous
    :meth:`DistributedKFAC.update_inverses` — sharded batched eigh over
    ``P(all_axes)``, then a resident-layout constraint on the shadow write
    (spreading the inverse-broadcast reshard across the window too).
    """
    from jax.sharding import NamedSharding

    cfg = engine.config
    hc = cfg.health
    h = state.health
    sh = state.shadow
    damping = _resolve(cfg.damping, state.step)
    dec = NamedSharding(engine.mesh, engine._decomp_spec())
    fields = decomp_fields(cfg.compute_method, engine._prediv)
    upd = {f: dict(getattr(sh, f)) for f in fields}

    def slot_damping(layers, padded):
        if hc is None:
            return damping
        return damping * engine._slot_mults(h, layers, padded)

    def store_by_key(store, key):
        return next(sb for sb in store if sb.key == key)

    for side, key in units:
        if engine._eigen:
            if side in ('a', 'ag'):
                q_, d_a = engine._sharded_eigh(state.a[key])
                upd['qa'][key] = jax.lax.with_sharding_constraint(
                    q_.astype(cfg.inv_dtype), dec
                )
                if not engine._prediv:
                    upd['da'][key] = jax.lax.with_sharding_constraint(
                        d_a.astype(cfg.inv_dtype), dec
                    )
            if side in ('g', 'ag'):
                q_, d_g = engine._sharded_eigh(state.g[key])
                upd['qg'][key] = jax.lax.with_sharding_constraint(
                    q_.astype(cfg.inv_dtype), dec
                )
                if not engine._prediv:
                    upd['dg'][key] = jax.lax.with_sharding_constraint(
                        d_g.astype(cfg.inv_dtype), dec
                    )
            if side == 'ag':
                b = store_by_key(engine.buckets, key)
                fused = jax.vmap(
                    lambda da_, dg_, dm: factors_lib.prediv_eigenvalues(
                        factors_lib.EigenDecomp(q=None, d=da_),
                        factors_lib.EigenDecomp(q=None, d=dg_),
                        dm,
                    )
                )(
                    d_a, d_g,
                    jnp.broadcast_to(
                        jnp.asarray(
                            slot_damping(b.layers, b.padded), jnp.float32
                        ),
                        (b.padded,),
                    ),
                )
                upd['dgda'][key] = jax.lax.with_sharding_constraint(
                    fused.astype(cfg.inv_dtype), dec
                )
        else:
            sb = store_by_key(
                engine.a_store if side == 'a' else engine.g_store, key
            )
            factor = state.a[key] if side == 'a' else state.g[key]
            prev = state.a_inv[key] if side == 'a' else state.g_inv[key]
            cand = engine._sharded_inv(
                factor, slot_damping(sb.layers, sb.padded), prev=prev
            ).astype(cfg.inv_dtype)
            upd['a_inv' if side == 'a' else 'g_inv'][key] = (
                jax.lax.with_sharding_constraint(cand, dec)
            )
    return state._replace(shadow=sh._replace(
        progress=sh.progress + 1,
        damping=jnp.asarray(damping, jnp.float32),
        **upd,
    ))


@tracing.scope('dist_kfac.async_refresh')
def kaisa_async_step(engine, state):
    """The distributed engine's in-jit async dispatcher (replaces the
    inverse cadence cond). Same three stages as
    :func:`dense_async_step`."""
    phase = jnp.mod(state.step, engine._async_n_steps)
    state = jax.lax.cond(
        phase == 0, partial(_kaisa_swap, engine), lambda s: s, state
    )
    state = jax.lax.cond(
        state.step == 0, engine.update_inverses, lambda s: s, state
    )
    n_slices = engine._async_n_slices
    branches = [
        partial(_kaisa_slice, engine, units=u) for u in engine._async_slices
    ] + [lambda s: s]
    return jax.lax.switch(jnp.minimum(phase, n_slices), branches, state)
