"""Shadow-slot state and slice planning for async inverse refresh.

The double buffer: every decomposition field of the engine state
(``qa``/``qg``/``da``/``dg``/``dgda`` or ``a_inv``/``g_inv``) gets a
*shadow* twin of identical shape. Slices (or the host worker) write into
the shadow; the window-boundary swap promotes a complete, finite,
non-quarantined shadow into the active slots in one gated ``where`` — a
step program never observes a half-written decomposition.

``ShadowSlots`` is engine-agnostic: the dense engine keys the dicts by
layer name, the distributed engine by storage-bucket key (stacked slots),
exactly mirroring the active fields. ``progress`` counts completed slices
since the last boundary — the swap's completeness gate — and ``damping``
records the damping the shadow was built at (promoted into the
distributed engine's ``inv_damping`` at swap time).

Shadow slots are deliberately EPHEMERAL: ``checkpoint.durable_state``
persists only ``step``/``a``/``g``(+health), so a restore rebuilds the
active decompositions synchronously (``rematerialize``) and resets the
shadow to empty. The first boundary after a mid-window restore finds
``progress < n_slices`` and skips the swap — deterministic, no torn slot.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class ShadowSlots(NamedTuple):
    """Shadow twins of the decomposition fields plus refresh bookkeeping.

    Unused method slots hold empty dicts so the pytree structure is
    static per-configuration (same contract as the engine state).
    """

    qa: dict[str, jax.Array]
    qg: dict[str, jax.Array]
    da: dict[str, jax.Array]
    dg: dict[str, jax.Array]
    dgda: dict[str, jax.Array]
    a_inv: dict[str, jax.Array]
    g_inv: dict[str, jax.Array]
    # completed slices since the last window boundary (int32 scalar)
    progress: jax.Array
    # damping the shadow decompositions were built at (f32 scalar); the
    # distributed engine promotes this into inv_damping at swap time
    damping: jax.Array


def empty_shadow(
    fields: dict[str, dict[str, jax.Array]],
) -> ShadowSlots:
    """A zeroed shadow mirroring ``fields`` (field name -> keyed arrays).

    Fields not present get empty dicts. ``progress`` starts at 0 so the
    first boundary after init/restore never swaps a never-written shadow.
    """
    slots = {
        f: {k: jnp.zeros_like(v) for k, v in fields.get(f, {}).items()}
        for f in ('qa', 'qg', 'da', 'dg', 'dgda', 'a_inv', 'g_inv')
    }
    return ShadowSlots(
        progress=jnp.zeros((), jnp.int32),
        damping=jnp.zeros((), jnp.float32),
        **slots,
    )


def plan_slices(
    units: list[tuple[Any, float]],
    n_slices: int,
) -> list[list[Any]]:
    """Greedy longest-processing-time balance of refresh units into slices.

    ``units`` is ``[(key, cost)]`` with cost in the n^3 compute weighting
    of :func:`kfac_tpu.assignment.compute_work_costs` (eigendecomposition
    FLOPs — the same heuristic KAISA's greedy placement balances with,
    reference kfac/assignment.py:227-319). Deterministic: ties break on
    the unit key's repr, then insertion order, so the slice plan — and
    therefore the compiled step program — is stable across processes.
    """
    if n_slices < 1:
        raise ValueError(f'n_slices must be >= 1, got {n_slices}')
    n_slices = min(n_slices, len(units)) or 1
    order = sorted(
        enumerate(units), key=lambda iu: (-iu[1][1], repr(iu[1][0]), iu[0])
    )
    loads = [0.0] * n_slices
    slices: list[list[Any]] = [[] for _ in range(n_slices)]
    for _, (key, cost) in order:
        tgt = min(range(n_slices), key=lambda i: (loads[i], i))
        slices[tgt].append(key)
        loads[tgt] += cost
    return [s for s in slices if s]
