"""Async curvature refresh: double-buffered inverses off the step path.

See :mod:`kfac_tpu.async_inverse.config` for the model, ``sliced`` for
the in-step sliced backend, ``host`` for the host-offloaded backend, and
``slots`` for the shadow-slot state + slice planner.
"""

from kfac_tpu.async_inverse.config import AsyncInverseConfig, as_async_config
from kfac_tpu.async_inverse.slots import ShadowSlots, plan_slices

__all__ = [
    'AsyncInverseConfig',
    'ShadowSlots',
    'as_async_config',
    'plan_slices',
]
