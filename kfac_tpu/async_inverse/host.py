"""Host-offloaded async refresh: the window's decompositions on a worker thread.

Extends the spirit of ``batched_eigh(impl='host')`` into a truly
asynchronous path. At each window boundary the step program ships the
freshly-updated factors (and the effective dampings they should be
decomposed at) to a host worker thread via ``io_callback`` — the device
keeps stepping while LAPACK does the eigh/inverse work on the host. The
worker ``device_put``s the finished payload back (an async transfer into
what is conceptually the shadow slot); at the NEXT boundary the Trainer
promotes it atomically through the same swap cores the sliced backend
uses, so health gating, quarantine discard, and ``last_inv_step``
accounting are identical.

The step program itself contains no decomposition work at all — only the
step-0 synchronous cold start and the boundary launch callback. Results
are numerically equivalent to the synchronous path (same math, LAPACK vs
XLA eigh) but not bit-identical, and the active decompositions are one
window staler — the same staleness contract as the sliced backend.

Driving: the Trainer pumps the worker on every step path. ``pump`` with a
step number applies only at window boundaries (blocking until the
in-flight refresh lands, preserving the boundary-atomic swap); ``pump``
without one (the scan paths, where the host cannot intervene mid-scan)
applies any completed payload at scan entry. An engine stepped without a
driver never swaps — it simply keeps applying the last promoted
decompositions, growing stale but never torn.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from kfac_tpu import enums
from kfac_tpu import tracing
from kfac_tpu.async_inverse import sliced as sliced_lib


class HostRefreshWorker:
    """A daemon thread running decomposition jobs off the step path.

    ``submit`` (called from an ``io_callback``) enqueues a job and returns
    immediately; the thread computes and keeps the LATEST completed
    payload (an overwritten result means the driver skipped a window —
    the fresher decomposition wins). ``take`` drains it, optionally
    blocking until the in-flight job lands (the boundary-pump case).
    ``reset`` invalidates in-flight work after a checkpoint restore.
    """

    def __init__(self, compute: Callable[..., Any]):
        self._compute = compute
        self._jobs: queue.Queue = queue.Queue()
        self._cv = threading.Condition()
        self._pending = 0
        self._result: Any = None
        self._epoch = 0
        self._last_step = -1
        self._error: BaseException | None = None
        self._thread: threading.Thread | None = None

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name='kfac-async-refresh', daemon=True
            )
            self._thread.start()

    def submit(self, *args) -> None:
        # io_callback may hand us buffers the runtime reuses — copy now
        args = jax.tree.map(np.array, args)
        with self._cv:
            self._pending += 1
            epoch = self._epoch
        self._jobs.put((epoch, args))
        self._ensure_thread()

    def _run(self) -> None:
        while True:
            epoch, args = self._jobs.get()
            # by convention the first submit arg is the launch step; the
            # boundary callbacks are unordered (see the launch sites), so
            # guard against an older window's job landing after a newer one
            step = int(np.asarray(args[0]))
            out, err = None, None
            try:
                out = self._compute(*args)
            except BaseException as e:  # surfaced on the next take()
                err = e
            with self._cv:
                self._pending -= 1
                if epoch == self._epoch:
                    if err is not None:
                        self._error = err
                    elif out is not None and step >= self._last_step:
                        self._result = out
                        self._last_step = step
                self._cv.notify_all()

    def has_work(self) -> bool:
        with self._cv:
            return (
                self._pending > 0
                or self._result is not None
                or self._error is not None
            )

    def take(self, wait: bool = False, timeout: float = 300.0) -> Any:
        """The latest completed payload, or None if nothing has landed.

        With ``wait=True``, blocks until the in-flight job finishes (the
        window-boundary pump must not swap a torn refresh, so it waits for
        the whole payload). Worker exceptions re-raise here.
        """
        with self._cv:
            if wait:
                self._cv.wait_for(
                    lambda: self._pending == 0, timeout=timeout
                )
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError(
                    'async inverse host refresh failed'
                ) from err
            if self._pending > 0 and not wait:
                return None
            result, self._result = self._result, None
            return result

    def reset(self) -> None:
        """Discard in-flight and completed work (post-restore: the factors
        that produced it no longer match the restored state)."""
        with self._cv:
            self._epoch += 1
            self._result = None
            self._error = None
            self._last_step = -1


def _worker(engine, compute_builder) -> HostRefreshWorker:
    if engine._async_worker is None:
        engine._async_worker = HostRefreshWorker(compute_builder(engine))
    return engine._async_worker


def reset_worker(engine) -> None:
    w = getattr(engine, '_async_worker', None)
    if w is not None:
        w.reset()


# --------------------------------------------------------------------- dense


def _dense_compute(engine):
    """Host-side refresh for the dense engine: numpy LAPACK, fp32.

    Mirrors ``update_inverses``: eigh with eigenvalues clipped to >= 0
    (PSD factors; tiny negative eigenvalues are roundoff), fused prediv
    ``1 / (outer(dg, da) + eff)``, or the damped INVERSE path. Payloads
    are ``device_put`` from the worker thread so the transfer overlaps
    training and the boundary apply finds the data already on device.
    """
    eigen = engine.compute_method == enums.ComputeMethod.EIGEN
    prediv = engine.prediv_eigenvalues
    fields = sliced_lib.decomp_fields(
        engine.compute_method, engine.prediv_eigenvalues
    )

    def compute(step, damping, effs, a, g):
        del step
        out: dict[str, dict[str, np.ndarray]] = {f: {} for f in fields}
        for name in a:
            eff = float(np.asarray(effs[name]))
            fa = np.asarray(a[name], np.float32)
            fg = np.asarray(g[name], np.float32)
            if eigen:
                wa, va = np.linalg.eigh(fa)
                wg, vg = np.linalg.eigh(fg)
                wa = np.clip(wa, 0.0, None)
                wg = np.clip(wg, 0.0, None)
                out['qa'][name] = va
                out['qg'][name] = vg
                if prediv:
                    out['dgda'][name] = (
                        1.0 / (np.outer(wg, wa) + eff)
                    ).astype(np.float32)
                else:
                    out['da'][name] = wa
                    out['dg'][name] = wg
            else:
                eye_a = np.eye(fa.shape[0], dtype=np.float32)
                eye_g = np.eye(fg.shape[0], dtype=np.float32)
                out['a_inv'][name] = np.linalg.inv(fa + eff * eye_a)
                out['g_inv'][name] = np.linalg.inv(fg + eff * eye_g)
        return {
            'fields': jax.tree.map(jax.device_put, out),
            'damping': float(np.asarray(damping)),
        }

    return compute


@tracing.scope('kfac.async_host_launch')
def dense_host_step(engine, state):
    """The dense engine's in-jit host-mode stage: cold start + boundary
    launch. No decomposition work runs on-device after step 0."""
    from jax.experimental import io_callback

    worker = _worker(engine, _dense_compute)
    state = jax.lax.cond(
        state.step == 0, engine.update_inverses, lambda s: s, state
    )

    def launch(s):
        damping = sliced_lib._resolve(engine.damping, s.step)
        if engine.health is None:
            effs = {
                n: jnp.asarray(damping, jnp.float32)
                for n in engine.registry.layers
            }
        else:
            effs = {
                n: jnp.asarray(
                    damping * s.health.damping_mult[n], jnp.float32
                )
                for n in engine.registry.layers
            }
        # ordered=True hard-crashes XLA's sharding propagation when the
        # callback sits inside a lax.cond branch with sharded operands
        # (sharding_propagation.cc CHECK on the parameter-propagation
        # vector); unordered callbacks compile and still fire only when
        # the branch is taken. The worker's step guard restores ordering.
        io_callback(
            worker.submit, None,
            s.step, jnp.asarray(damping, jnp.float32), effs, s.a, s.g,
            ordered=False,
        )
        return s

    return jax.lax.cond(
        jnp.mod(state.step, engine._async_n_steps) == 0,
        launch, lambda s: s, state,
    )


def dense_apply(engine, state, payload):
    """Promote a completed host payload through the shared swap core."""
    cand = {
        f: {
            n: jnp.asarray(v).astype(engine.inv_dtype)
            for n, v in d.items()
        }
        for f, d in payload['fields'].items()
    }
    return sliced_lib.dense_swap_core(engine, state, cand, complete=True)


# --------------------------------------------------------------- distributed


def _kaisa_compute(engine):
    """Host-side refresh for the distributed engine: batched numpy LAPACK
    over the full stacked slots (the host sees the gathered stacks)."""
    cfg = engine.config
    eigen = engine._eigen
    prediv = engine._prediv
    fields = sliced_lib.decomp_fields(cfg.compute_method, prediv)
    from jax.sharding import NamedSharding, PartitionSpec

    rep = NamedSharding(engine.mesh, PartitionSpec())

    def compute(step, damping, dmp_a, dmp_g, dmp_pair, a, g):
        del step
        out: dict[str, dict[str, np.ndarray]] = {f: {} for f in fields}
        if eigen:
            d_a: dict[str, np.ndarray] = {}
            d_g: dict[str, np.ndarray] = {}
            for key, stack in a.items():
                w, v = np.linalg.eigh(np.asarray(stack, np.float32))
                d_a[key] = np.clip(w, 0.0, None)
                out['qa'][key] = v
                if not prediv:
                    out['da'][key] = d_a[key]
            for key, stack in g.items():
                w, v = np.linalg.eigh(np.asarray(stack, np.float32))
                d_g[key] = np.clip(w, 0.0, None)
                out['qg'][key] = v
                if not prediv:
                    out['dg'][key] = d_g[key]
            if prediv:
                for key, dmp in dmp_pair.items():
                    dmp = np.asarray(dmp, np.float32)
                    out['dgda'][key] = (
                        1.0
                        / (
                            d_g[key][:, :, None] * d_a[key][:, None, :]
                            + dmp[:, None, None]
                        )
                    ).astype(np.float32)
        else:
            for key, stack in a.items():
                f32 = np.asarray(stack, np.float32)
                dmp = np.asarray(dmp_a[key], np.float32)
                eye = np.eye(f32.shape[-1], dtype=np.float32)
                out['a_inv'][key] = np.linalg.inv(
                    f32 + dmp[:, None, None] * eye
                )
            for key, stack in g.items():
                f32 = np.asarray(stack, np.float32)
                dmp = np.asarray(dmp_g[key], np.float32)
                eye = np.eye(f32.shape[-1], dtype=np.float32)
                out['g_inv'][key] = np.linalg.inv(
                    f32 + dmp[:, None, None] * eye
                )
        return {
            'fields': jax.tree.map(
                lambda x: jax.device_put(x, rep), out
            ),
            'damping': float(np.asarray(damping)),
        }

    return compute


@tracing.scope('dist_kfac.async_host_launch')
def kaisa_host_step(engine, state):
    """The distributed engine's in-jit host-mode stage."""
    from jax.experimental import io_callback

    worker = _worker(engine, _kaisa_compute)
    cfg = engine.config
    state = jax.lax.cond(
        state.step == 0, engine.update_inverses, lambda s: s, state
    )

    def launch(s):
        damping = sliced_lib._resolve(cfg.damping, s.step)

        def slot_dmp(layers, padded):
            if cfg.health is None:
                base = jnp.asarray(damping, jnp.float32)
            else:
                base = jnp.asarray(
                    damping * engine._slot_mults(s.health, layers, padded),
                    jnp.float32,
                )
            return jnp.broadcast_to(base, (padded,))

        dmp_a = {
            sb.key: slot_dmp(sb.layers, sb.padded) for sb in engine.a_store
        }
        dmp_g = {
            sb.key: slot_dmp(sb.layers, sb.padded) for sb in engine.g_store
        }
        dmp_pair = (
            {b.key: slot_dmp(b.layers, b.padded) for b in engine.buckets}
            if engine._prediv else {}
        )
        # unordered for the same XLA cond+sharded-operand crash as the
        # dense launch; the worker's step guard restores ordering
        io_callback(
            worker.submit, None,
            s.step, jnp.asarray(damping, jnp.float32),
            dmp_a, dmp_g, dmp_pair, s.a, s.g,
            ordered=False,
        )
        return s

    return jax.lax.cond(
        jnp.mod(state.step, engine._async_n_steps) == 0,
        launch, lambda s: s, state,
    )


def kaisa_apply(engine, state, payload):
    """Promote a completed host payload through the shared swap core."""
    cfg = engine.config
    cand = {
        f: {
            k: jnp.asarray(v).astype(cfg.inv_dtype)
            for k, v in d.items()
        }
        for f, d in payload['fields'].items()
    }
    return sliced_lib.kaisa_swap_core(
        engine, state, cand,
        jnp.asarray(payload['damping'], jnp.float32),
        complete=True,
    )


# --------------------------------------------------------------------- pump


def _apply_fn(engine):
    fn = getattr(engine, '_async_apply_cache', None)
    if fn is None:
        if hasattr(engine, '_sharded_eigh'):  # distributed engine
            fn = jax.jit(
                lambda s, p: kaisa_apply(engine, s, p),
                out_shardings=engine.state_shardings(),
            )
        else:
            fn = jax.jit(lambda s, p: dense_apply(engine, s, p))
        engine._async_apply_cache = fn
    return fn


@tracing.trace(name='kfac.async_host_pump')
def pump(engine, state, step: int | None = None):
    """Host-side driver: promote a completed refresh into the state.

    With ``step``: apply only at a window boundary, blocking until the
    in-flight refresh lands (swap stays boundary-atomic; the wait is the
    host analogue of the synchronous spike and is ~0 when the window gave
    the worker enough time). Without ``step`` (the scan paths — the host
    cannot intervene mid-scan): apply any already-completed payload,
    non-blocking. Returns the (possibly swapped) state.
    """
    if getattr(engine, '_async_mode', None) != 'host':
        return state
    worker = engine._async_worker
    if worker is None or not worker.has_work():
        return state
    if step is not None:
        if step <= 0 or step % engine._async_n_steps != 0:
            return state
        payload = worker.take(wait=True)
    else:
        payload = worker.take(wait=False)
    if payload is None:
        return state
    return _apply_fn(engine)(state, payload)
