"""Configuration for asynchronous (double-buffered) inverse refresh.

The cadence machinery already tolerates stale inverses by design — the
engine applies the PREVIOUS decomposition for a whole ``inv_update_steps``
window. Async refresh exploits that tolerance: instead of recomputing every
decomposition synchronously at the window boundary (a 30*d^3 spike on one
step), the refresh runs as an overlapped side computation into a *shadow*
slot and is swapped in atomically at the next boundary. The active
decompositions a step applies are therefore exactly one window staler than
the synchronous path's — the same freshness contract, shifted by N steps
(cf. Distributed Shampoo's asynchronous preconditioner computation, Anil et
al. 2021, and Osawa et al. 2019's pipelined K-FAC stages).

Two backends:

- ``'sliced'``: the window's decomposition work is split into per-step
  slices balanced by the n^3 compute weighting
  (:func:`kfac_tpu.assignment.compute_work_costs`), executed inside the
  step program. No step absorbs the full eigh cost; everything stays
  on-device and the swapped results are bit-identical to what the
  synchronous path would have computed from the same factors.
- ``'host'``: the whole window's decomposition is shipped to a host worker
  thread via ``io_callback`` at the boundary, computed with LAPACK while
  the device keeps stepping, and device_put back for the next boundary's
  swap. The step program contains no decomposition work at all; results
  are numerically equivalent (same math, LAPACK vs XLA eigh) but not
  bit-identical. Requires a host-side driver for the swap — the Trainer
  drives it on all four step paths; a bare engine stepped without a driver
  simply keeps applying the last swapped decompositions.
"""

from __future__ import annotations

import dataclasses
from typing import Any

MODES = ('sliced', 'host')


@dataclasses.dataclass(frozen=True)
class AsyncInverseConfig:
    """Knobs for the async refresh subsystem.

    Args:
        mode: ``'sliced'`` (in-step sliced refresh) or ``'host'``
            (host-offloaded refresh). See the module docstring.
        max_slices: optional cap on the number of per-step slices in
            ``'sliced'`` mode. By default the planner uses
            ``min(inv_update_steps, n_units)`` slices (one unit bucket per
            step); a cap packs more units per slice, finishing the refresh
            earlier in the window at a higher per-step cost.
    """

    mode: str = 'sliced'
    max_slices: int | None = None

    def __post_init__(self) -> None:
        if self.mode not in MODES:
            raise ValueError(
                f'unknown async_inverse mode {self.mode!r}; expected one '
                f'of {MODES}'
            )
        if self.max_slices is not None and self.max_slices < 1:
            raise ValueError(
                f'max_slices must be >= 1 (or None), got {self.max_slices}'
            )


def as_async_config(value: Any) -> AsyncInverseConfig | None:
    """Normalize the ``async_inverse=`` constructor surface.

    Accepts ``None`` (disabled), a mode string (``'sliced'``/``'host'``),
    ``True`` (sliced defaults), or an :class:`AsyncInverseConfig`.
    """
    if value is None or value is False:
        return None
    if value is True:
        return AsyncInverseConfig()
    if isinstance(value, str):
        return AsyncInverseConfig(mode=value)
    if isinstance(value, AsyncInverseConfig):
        return value
    raise TypeError(
        'async_inverse must be an AsyncInverseConfig, a mode string '
        f'({MODES}), True, False, or None; got {value!r}'
    )
