#!/usr/bin/env python
"""Lint: the metric-key tables in docs/OBSERVABILITY.md match the code.

The drained-record schema is a *contract* — dashboards, the flight
recorder's ring columns, and ``tools/kfac_inspect.py`` all key off it —
so the documentation tables under '### Metric-key schema' must stay in
lockstep with :func:`kfac_tpu.observability.metric_keys` and
:func:`kfac_tpu.health.health_metric_keys`. This script parses the
backticked keys out of those two tables (``<layer>`` rows compared with a
literal ``<layer>`` placeholder name) and fails on any drift in either
direction.

Run via ``make obs`` (CPU-pinned) or directly:

    JAX_PLATFORMS=cpu python tools/lint_metric_keys.py
"""

from __future__ import annotations

import os
import re
import sys

DOC = 'docs/OBSERVABILITY.md'
SECTION = '### Metric-key schema'

#: documented keys that are drain-record fields, not metric_keys entries
EXTRA_DOC_KEYS = {'step'}


def _doc_section(text: str) -> str:
    start = text.index(SECTION)
    rest = text[start + len(SECTION):]
    m = re.search(r'^#{2,3} ', rest, re.MULTILINE)
    return rest[: m.start()] if m else rest


def doc_keys(doc_path: str) -> set[str]:
    """Backticked keys from the first column of the section's tables."""
    with open(doc_path) as f:
        section = _doc_section(f.read())
    keys: set[str] = set()
    for line in section.splitlines():
        line = line.strip()
        # table rows whose first cell is one or more `key` tokens; the
        # header/separator rows and prose paragraphs never match
        if not line.startswith('| `'):
            continue
        first_cell = line.split('|')[1]
        keys.update(re.findall(r'`([^`]+)`', first_cell))
    return keys


def code_keys() -> set[str]:
    from kfac_tpu import health
    from kfac_tpu.observability import metrics as metrics_lib

    names = ['<layer>']
    keys = set(metrics_lib.metric_keys(metrics_lib.MetricsConfig(), names))
    keys |= set(health.health_metric_keys(names))
    return keys | EXTRA_DOC_KEYS


def check(doc_path: str = DOC) -> list[str]:
    """Return human-readable drift complaints (empty = in sync)."""
    documented = doc_keys(doc_path)
    actual = code_keys()
    problems = []
    for k in sorted(actual - documented):
        problems.append(f'undocumented key (add to {DOC}): {k}')
    for k in sorted(documented - actual):
        problems.append(f'documented key not produced by the code: {k}')
    return problems


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    # the repo is not pip-installed; make `python tools/...` work from root
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    os.chdir(repo_root)
    problems = check()
    if problems:
        print('metric-key schema drift between code and docs:')
        for p in problems:
            print(f'  {p}')
        return 1
    print(f'metric-key lint ok: {len(doc_keys(DOC))} documented keys '
          'match metric_keys() + health_metric_keys()')
    return 0


if __name__ == '__main__':
    sys.exit(main())
