#!/usr/bin/env python
"""Lint: the metric-key tables in docs/OBSERVABILITY.md match the code.

Thin wrapper kept for ``make obs`` and existing imports; the check now
lives in the kfaclint registry as rule **KFL102** (see
``kfac_tpu/analysis/drift.py`` and docs/ANALYSIS.md). Prefer:

    JAX_PLATFORMS=cpu python tools/kfaclint.py --rules KFL102
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: E402

_common.bootstrap()

from kfac_tpu.analysis import drift  # noqa: E402

DOC = drift.OBSERVABILITY_DOC


def check(doc_path: str = DOC) -> list[str]:
    """Return human-readable drift complaints (empty = in sync)."""
    return drift.check_metric_keys(doc_path)


def main() -> int:
    problems = check()
    if problems:
        print('metric-key schema drift between code and docs:')
        for p in problems:
            print(f'  {p}')
        return 1
    section, _ = drift.doc_section(DOC, '### Metric-key schema')
    n = len(drift.table_first_cells(section))
    print(f'metric-key lint ok: {n} documented keys '
          'match metric_keys() + health_metric_keys()')
    return 0


if __name__ == '__main__':
    sys.exit(main())
