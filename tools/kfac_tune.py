#!/usr/bin/env python
"""Offline layout-autotuner CLI: search the KAISA knobs, write a TunedPlan.

Runs the ``kfac_tpu.autotune`` search — analytic cost-model ranking over
the gradient-worker-fraction x bucket-granularity x transport x
inverse-cadence grid, then timed trials of the top-K real
``DistributedKFAC`` engines plus the three hand-configured strategy
baselines — on a benchmark MLP config shaped like your model, and writes
the winning knobs as a versioned JSON plan:

    python tools/kfac_tune.py --d-model 512 --layers 4 --out plan.json

Training then picks the plan up with
``Trainer(..., auto_layout='plan.json')`` or
``DistributedKFAC(config, auto_layout='plan.json')`` — applied only when
the topology+model fingerprint matches, ignored with a rate-limited
warning otherwise. ``bench.py`` records the active plan (set
``KFAC_TUNE_PLAN=plan.json``) into its run JSON.

``--selftest`` (wired into ``make tune``) runs the whole pipeline on a
tiny config and asserts the plan round-trips, is deterministic, applies,
and is rejected on a tampered fingerprint.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any


def _pin_host_platform() -> None:
    """Default to the 8-virtual-device CPU mesh when no platform was
    pinned (the same environment the test suite runs against); a real
    TPU run sets JAX_PLATFORMS/XLA_FLAGS itself."""
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    os.environ.setdefault('PALLAS_AXON_POOL_IPS', '')
    os.environ.setdefault(
        'XLA_FLAGS', '--xla_force_host_platform_device_count=8'
    )


def build_benchmark(args: argparse.Namespace):
    """(base config, loss_fn, params, batch) for an MLP shaped by the
    CLI flags — the stand-in for the real model's layer-dimension mix."""
    import jax
    import jax.numpy as jnp

    import kfac_tpu
    from kfac_tpu.models import MLP

    model = MLP(
        features=(args.d_model,) * args.layers, num_classes=args.classes
    )
    x = jax.random.normal(
        jax.random.PRNGKey(args.seed), (args.batch, args.d_in)
    )
    registry = kfac_tpu.register_model(model, x)
    params = model.init(jax.random.PRNGKey(args.seed + 1), x)['params']
    base = kfac_tpu.KFACPreconditioner(
        registry=registry,
        damping=args.damping,
        lr=0.1,
        factor_update_steps=args.factor_update_steps,
        inv_update_steps=args.inv_update_steps,
    )

    def loss_fn(p: Any, batch: Any):
        return jnp.mean(model.apply({'params': p}, batch) ** 2)

    return base, loss_fn, params, x


def _csv_ints(s: str) -> tuple[int, ...]:
    return tuple(int(v) for v in s.split(',') if v.strip())


def _topo_col(knobs: dict[str, Any]) -> str:
    topo = knobs.get('topology')
    if not topo:
        return ''
    return (
        f"dp{topo['dp']}.tp{topo['tp']}.pp{topo['pp']} "
        f"v={topo['virtual_chunks']} m={topo['microbatches']} "
        f"{topo['schedule']:<11} "
    )


def summarize(plan: Any) -> str:
    lines = [
        f'TunedPlan (schema {plan.schema}): winner '
        f'{_topo_col(plan.knobs)}'
        f'{plan.knobs["strategy"]} frac={plan.knobs["grad_worker_fraction"]} '
        f'granularity={plan.knobs["bucket_granularity"]} '
        f'transport={plan.knobs["allreduce_method"]} '
        f'picked_by={plan.winner["picked_by"]}',
        'cost table (best-ranked first):',
    ]
    for row in plan.cost_table[:10]:
        k = row['knobs']
        meas = (
            f'{row["measured_step_s"]*1e3:8.2f} ms'
            if row.get('measured_step_s') is not None else '       --'
        )
        feas = '' if row['feasible'] else '  INFEASIBLE'
        lines.append(
            f'  {_topo_col(k)}'
            f'{k["strategy"]:>10} frac={k["grad_worker_fraction"]:<7.4g} '
            f'gran={k["bucket_granularity"]:<4} '
            f'{k["allreduce_method"]:<19} '
            f'pred {row["predicted_step_s"]*1e6:9.2f} us  '
            f'meas {meas}{feas}'
        )
    if len(plan.cost_table) > 10:
        lines.append(f'  ... {len(plan.cost_table) - 10} more rows')
    return '\n'.join(lines)


def run_search(args: argparse.Namespace) -> int:
    from kfac_tpu import autotune

    base, loss_fn, params, batch = build_benchmark(args)
    hardware = autotune.HardwareSpec(
        hbm_bytes=None if args.hbm_gb is None else args.hbm_gb * 2**30
    )
    if args.topology:
        # the 3D planner is predict-only: bubble fractions come from the
        # executed-schedule simulators + the committed measured table
        plan = autotune.autotune(
            base, measure=False, hardware=hardware, topology=True,
        )
        if args.json:
            json.dump(plan.to_json(), sys.stdout, indent=2, sort_keys=True)
            print()
        else:
            print(summarize(plan))
        if args.out:
            plan.save(args.out)
            print(f'wrote {args.out}')
        return 0
    plan = autotune.autotune(
        base,
        None if args.no_measure else loss_fn,
        params,
        batch,
        top_k=args.top_k,
        measure=not args.no_measure,
        hardware=hardware,
        granularities=_csv_ints(args.granularities),
        inv_cadences=(
            _csv_ints(args.inv_cadences) if args.inv_cadences else None
        ),
        warmup=args.warmup,
        iters=args.iters,
    )
    if args.json:
        json.dump(plan.to_json(), sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        print(summarize(plan))
    if args.out:
        plan.save(args.out)
        print(f'wrote {args.out}')
    return 0


# ---------------------------------------------------------------- selftest


def selftest() -> int:
    import tempfile
    import warnings as pywarnings

    import kfac_tpu
    from kfac_tpu import autotune
    from kfac_tpu.parallel.kaisa import DistributedKFAC
    from kfac_tpu.parallel.mesh import kaisa_mesh
    from kfac_tpu.warnings import LayoutPlanWarning, reset_layout_warnings

    args = argparse.Namespace(
        d_model=16, layers=2, classes=4, batch=8, d_in=12, seed=0,
        damping=1e-3, factor_update_steps=1, inv_update_steps=1,
    )
    base, loss_fn, params, batch = build_benchmark(args)

    # deterministic model-only plan
    p1 = autotune.autotune(base, measure=False)
    p2 = autotune.autotune(base, measure=False)
    assert p1.to_json() == p2.to_json(), 'model-ranked plan not deterministic'

    # tiny measured run: the winner must not lose to any measured baseline
    plan = autotune.autotune(
        base, loss_fn, params, batch,
        top_k=1, warmup=0, iters=2, granularities=(1,),
    )
    measured = [
        r['measured_step_s'] for r in plan.cost_table if r['measured']
    ]
    assert measured and plan.winner['measured_step_s'] == min(measured)

    # round trip + application
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, 'plan.json')
        plan.save(path)
        loaded = kfac_tpu.TunedPlan.load(path)
        assert loaded.to_json() == plan.to_json(), 'round trip drift'
        eng = DistributedKFAC(config=base, auto_layout=path)
        assert eng.auto_layout_applied
        frac = plan.knobs['grad_worker_fraction']
        ref = DistributedKFAC(
            config=autotune.apply_knobs(base, plan.knobs),
            mesh=kaisa_mesh(grad_worker_fraction=frac),
        )
        assert eng.comms_report() == ref.comms_report(), 'plan != knobs'

    # tampered fingerprint falls back with a rate-limited warning
    bad = plan.to_json()
    bad['fingerprint'] = dict(bad['fingerprint'], device_count=12345)
    reset_layout_warnings()
    with pywarnings.catch_warnings(record=True) as rec:
        pywarnings.simplefilter('always')
        eng = DistributedKFAC(config=base, auto_layout=bad)
    assert not eng.auto_layout_applied
    assert any(isinstance(r.message, LayoutPlanWarning) for r in rec)

    # 3D topology planner: a pp>1 plan that round-trips byte-identically
    # through save/load and resolves to a pipeline mesh
    from kfac_tpu.autotune import plan as plan_mod
    from kfac_tpu.parallel.mesh import PIPE_AXIS

    topo_plan = autotune.autotune(base, measure=False, topology=True)
    topo = topo_plan.knobs['topology']
    assert topo and topo['pp'] > 1, f'planner picked a flat mesh: {topo}'
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, 'topo_plan.json')
        topo_plan.save(path)
        with open(path) as f:
            raw1 = f.read()
        loaded = kfac_tpu.TunedPlan.load(path)
        assert loaded.to_json() == topo_plan.to_json(), 'topology round trip'
        loaded.save(path)
        with open(path) as f:
            raw2 = f.read()
        assert raw1 == raw2, 'topology plan save is not byte-stable'
        cfg2, mesh2, applied = plan_mod.resolve_auto_layout(
            base, None, loaded
        )
        assert applied, 'topology plan did not apply'
        assert dict(mesh2.shape)[PIPE_AXIS] == topo['pp']

    # a pre-planner plan document (no topology knob) still loads and
    # defaults to the flat layout
    legacy_doc = plan.to_json()
    legacy_doc['knobs'] = {
        k: val for k, val in legacy_doc['knobs'].items() if k != 'topology'
    }
    legacy = kfac_tpu.TunedPlan.from_json(legacy_doc)
    assert legacy.knobs['topology'] is None
    eng = DistributedKFAC(config=base, auto_layout=legacy)
    assert eng.auto_layout_applied, 'pre-planner plan no longer applies'

    print('kfac_tune selftest ok')
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument('--out', default=None,
                        help='write the TunedPlan JSON here')
    parser.add_argument('--json', action='store_true',
                        help='print the full plan JSON instead of a summary')
    parser.add_argument('--selftest', action='store_true',
                        help='run the end-to-end pipeline self-check')
    bench = parser.add_argument_group('benchmark model')
    bench.add_argument('--d-model', type=int, default=128)
    bench.add_argument('--layers', type=int, default=2)
    bench.add_argument('--d-in', type=int, default=64)
    bench.add_argument('--classes', type=int, default=10)
    bench.add_argument('--batch', type=int, default=64)
    bench.add_argument('--seed', type=int, default=0)
    bench.add_argument('--damping', type=float, default=1e-3)
    bench.add_argument('--factor-update-steps', type=int, default=1)
    bench.add_argument('--inv-update-steps', type=int, default=1)
    search = parser.add_argument_group('search')
    search.add_argument('--top-k', type=int, default=3)
    search.add_argument('--iters', type=int, default=5)
    search.add_argument('--warmup', type=int, default=1)
    search.add_argument('--no-measure', action='store_true',
                        help='model-ranked only (no timed trials)')
    search.add_argument('--granularities', default='1,64,128,256')
    search.add_argument('--inv-cadences', default='',
                        help='CSV of inverse cadences to widen the grid '
                             '(default: keep the base cadence)')
    search.add_argument('--hbm-gb', type=float, default=None,
                        help='per-device HBM budget for feasibility pruning')
    search.add_argument('--topology', action='store_true',
                        help='rank DP×TP×PP mesh factorizations with the '
                             '3D planner instead of the flat KAISA grid')
    args = parser.parse_args(argv)

    _pin_host_platform()
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _common
    _common.bootstrap()

    if args.selftest:
        return selftest()
    return run_search(args)


if __name__ == '__main__':
    sys.exit(main())
