#!/usr/bin/env python
"""Drift guard: docs/AUTOTUNE.md's plan-schema table vs the code.

Thin wrapper kept for ``make tune`` / ``make obs`` and existing imports;
the check now lives in the kfaclint registry as rule **KFL103** (see
``kfac_tpu/analysis/drift.py`` and docs/ANALYSIS.md). Prefer:

    JAX_PLATFORMS=cpu python tools/kfaclint.py --rules KFL103
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: E402

_common.bootstrap()

from kfac_tpu.analysis import drift  # noqa: E402

DOC = drift.AUTOTUNE_DOC


def check(doc_path: str = DOC) -> list[str]:
    return drift.check_plan_schema(doc_path)


def main() -> int:
    complaints = check()
    if complaints:
        print('\n'.join(complaints))
        return 1
    section, _ = drift.doc_section(DOC, '### Plan schema')
    n = len(drift.table_first_cells(section))
    print(
        f'plan-schema lint ok: {n} documented fields match '
        f'kfac_tpu.autotune.plan.plan_schema_keys()'
    )
    return 0


if __name__ == '__main__':
    sys.exit(main())
