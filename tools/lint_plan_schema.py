#!/usr/bin/env python
"""Drift guard: docs/AUTOTUNE.md's plan-schema table vs the code.

The TunedPlan JSON schema is documented as a table in docs/AUTOTUNE.md
(section '### Plan schema'). The set of keys the code actually
serializes is ``kfac_tpu.autotune.plan.plan_schema_keys()`` — the
top-level plan fields plus one ``knobs.<name>`` entry per knob. This
lint fails when either side drifts: a field added to the plan without a
doc row, or a documented field the code no longer produces.

Run directly or via ``make tune`` / ``make obs``.
"""

from __future__ import annotations

import os
import re
import sys

DOC = 'docs/AUTOTUNE.md'
SECTION = '### Plan schema'


def _doc_section(text: str) -> str:
    """The plan-schema section body (up to the next heading)."""
    try:
        start = text.index(SECTION)
    except ValueError:
        raise SystemExit(f'{DOC} has no "{SECTION}" section')
    rest = text[start + len(SECTION):]
    nxt = re.search(r'^#{2,3} ', rest, re.MULTILINE)
    return rest[: nxt.start()] if nxt else rest


def doc_keys(doc_path: str = DOC) -> set[str]:
    with open(doc_path, encoding='utf-8') as f:
        section = _doc_section(f.read())
    keys: set[str] = set()
    for line in section.splitlines():
        line = line.strip()
        if not line.startswith('| `'):
            continue
        first_cell = line.split('|')[1]
        keys.update(re.findall(r'`([^`]+)`', first_cell))
    return keys


def code_keys() -> set[str]:
    from kfac_tpu.autotune import plan as plan_lib

    return set(plan_lib.plan_schema_keys())


def check(doc_path: str = DOC) -> list[str]:
    documented = doc_keys(doc_path)
    produced = code_keys()
    complaints = []
    for k in sorted(produced - documented):
        complaints.append(f'undocumented plan field (add to {DOC}): {k}')
    for k in sorted(documented - produced):
        complaints.append(f'documented field not in the plan schema: {k}')
    return complaints


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    os.chdir(repo_root)
    complaints = check()
    if complaints:
        print('\n'.join(complaints))
        return 1
    print(
        f'plan-schema lint ok: {len(doc_keys())} documented fields match '
        f'kfac_tpu.autotune.plan.plan_schema_keys()'
    )
    return 0


if __name__ == '__main__':
    sys.exit(main())
