#!/usr/bin/env python
"""Offline triage for K-FAC telemetry: divergence timelines from JSONL
metric logs or flight-recorder postmortem bundles.

Given either

- a metrics JSONL file (``observability.JSONLWriter`` output, one record
  per drain), or
- a postmortem bundle directory written by
  ``observability.PostmortemWriter`` (detected by ``MANIFEST.json``),

this prints what a paged-in human needs first: *which layer went bad
first, and when* — the step each layer's factor bounds first blew up or
went non-finite, when damping escalated, when the KL clip started biting,
where skip-step gaps appear in the recorded step sequence, and the first
non-finite loss. For bundles it also summarizes the trigger, health
counters, topology fingerprint, the comms/padding report, and the
compile-watch event tail (compile counts, recompiles, XLA memory).

A third input kind is the compile-watch heartbeat journal
(``CompileWatchConfig.journal_path`` — ``phase: lowering -> compiling ->
done`` records, fsynced before each blocking phase). A journal whose
last heartbeat for some entry never reached ``done`` yields the
"died compiling X" verdict: the entry name, the phase it died in, and
the elapsed time the journal proves — the mid-compile postmortem the
live-tunnel sessions were missing (ROADMAP item 1). Mixed files work:
compile records and metric records are partitioned and each analyzed.

Deliberately dependency-free (stdlib only — no jax, no numpy): bundles
are meant to be inspected on any machine, including ones without the
training environment.

``--timeline`` routes the input through the unified run ledger
(``kfac_tpu/observability/ledger.py``, loaded standalone — still no jax)
instead of the two separate analyses: a run directory of stream files
(or one mixed JSONL) becomes a single correlated anomaly timeline where
the "died compiling X" verdict and the divergence first-bad-signal
verdict from the same run appear in ONE report, joined across streams by
the ledger's correlation rules (see docs/OBSERVABILITY.md "Run ledger").

Usage:

    python tools/kfac_inspect.py metrics.jsonl
    python tools/kfac_inspect.py postmortems/postmortem-step00000042-skip
    python tools/kfac_inspect.py --timeline tests/data/mini_ledger
    python tools/kfac_inspect.py --json BUNDLE_OR_JSONL
    python tools/kfac_inspect.py --selftest

Run via ``make inspect BUNDLE=...``; ``--selftest`` (wired into
``make obs``) checks the analysis against synthesized divergences.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Any

#: factor-bound magnitude treated as "blown up" — matches the health
#: sentinel's default quarantine_threshold
HUGE = 1e8

#: damping_eff ratio over its own first observed value that counts as an
#: escalation event (the sentinel's default escalation step is 10x)
DAMPING_JUMP = 2.0

#: kl_clip_scale below this means the clip is biting hard
KL_HARD = 0.5

#: event-kind severity order for first-bad-layer tie-breaks (worst first)
_SEVERITY = ('nonfinite_factor', 'huge_factor', 'damping_escalation')


def _finite(v: Any) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v)


# ----------------------------------------------------------------- loading


def load_jsonl(path: str) -> list[dict[str, Any]]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    records.sort(key=lambda r: r.get('step', -1))
    return records


def load_bundle(bdir: str) -> dict[str, Any]:
    """Read the JSON half of a postmortem bundle (history.npz is the
    lossless archive; the JSONL mirror is what triage needs)."""
    bundle: dict[str, Any] = {'dir': bdir}
    with open(os.path.join(bdir, 'MANIFEST.json')) as f:
        bundle['manifest'] = json.load(f)
    hist = os.path.join(bdir, 'history.jsonl')
    bundle['history'] = load_jsonl(hist) if os.path.exists(hist) else []
    events = os.path.join(bdir, 'compile_events.jsonl')
    bundle['compile_events'] = (
        load_jsonl(events) if os.path.exists(events) else [])
    for name in ('health', 'comms', 'fingerprint', 'factors',
                 'compile_memory'):
        path = os.path.join(bdir, f'{name}.json')
        if os.path.exists(path):
            with open(path) as f:
                bundle[name] = json.load(f)
    return bundle


def split_compile_records(
    records: list[dict[str, Any]],
) -> tuple[list[dict[str, Any]], list[dict[str, Any]]]:
    """Partition a JSONL into (compile heartbeats, metric records) so a
    compile-watch journal — or a mixed log — routes to both analyses."""
    compile_recs: list[dict[str, Any]] = []
    metric_recs: list[dict[str, Any]] = []
    for r in records:
        if r.get('kind') == 'compile' and 'phase' in r:
            compile_recs.append(r)
        else:
            metric_recs.append(r)
    return compile_recs, metric_recs


# ---------------------------------------------------------------- analysis


def analyze(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Divergence timeline over chronological drain/ring records.

    Returns ``{'events': [{'step', 'kind', 'layer'?, 'detail'}...],
    'first_bad_layer': {...}|None, 'steps': [lo, hi], 'n_records': N,
    'gaps': [[lo, hi]...]}``. Events are ordered by step, then severity.
    """
    events: list[dict[str, Any]] = []
    first_damping: dict[str, float] = {}
    seen: set[tuple[str, str]] = set()  # (kind, layer/key) fired once

    def fire(step: int, kind: str, layer: str | None, detail: str,
             dedup: str | None = None) -> None:
        key = (kind, dedup if dedup is not None else (layer or ''))
        if key in seen:
            return
        seen.add(key)
        ev: dict[str, Any] = {'step': step, 'kind': kind, 'detail': detail}
        if layer is not None:
            ev['layer'] = layer
        events.append(ev)

    steps = [int(r['step']) for r in records if 'step' in r]
    gaps: list[list[int]] = []
    for prev, cur in zip(steps, steps[1:]):
        if cur > prev + 1:
            gaps.append([prev + 1, cur - 1])

    for rec in records:
        step = int(rec.get('step', -1))
        loss = rec.get('loss')
        if loss is not None and not _finite(loss):
            fire(step, 'nonfinite_loss', None, f'loss = {loss}')
        for k, v in rec.items():
            if k.startswith(('factor_lmin/', 'factor_lmax/')):
                _, side, layer = k.split('/', 2)
                if not _finite(v):
                    fire(step, 'nonfinite_factor', layer,
                         f'{k} = {v}', dedup=f'{layer}/{side}')
                elif abs(v) >= HUGE:
                    fire(step, 'huge_factor', layer,
                         f'{k} = {v:.3g} (>= {HUGE:g})',
                         dedup=f'{layer}/{side}')
            elif k.startswith('damping_eff/') and _finite(v):
                layer = k.split('/', 1)[1]
                base = first_damping.setdefault(layer, float(v))
                if base > 0 and v >= DAMPING_JUMP * base:
                    fire(step, 'damping_escalation', layer,
                         f'{k}: {base:.3g} -> {v:.3g} '
                         f'({v / base:.1f}x)')
            elif k == 'kl_clip_scale' and _finite(v) and v < KL_HARD:
                fire(step, 'kl_clip_hard', None,
                     f'kl_clip_scale = {v:.3g} (< {KL_HARD})')
            elif k == 'grad_norm' and not _finite(v):
                fire(step, 'nonfinite_grad_norm', None, f'grad_norm = {v}')

    for lo, hi in gaps:
        n = hi - lo + 1
        events.append({
            'step': lo, 'kind': 'step_gap',
            'detail': (f'steps {lo}..{hi} unrecorded ({n} missing — '
                       'skip-step gate or drain cadence)'),
        })

    sev = {k: i for i, k in enumerate(_SEVERITY)}
    events.sort(key=lambda e: (e['step'], sev.get(e['kind'], len(sev))))

    first_bad = None
    for ev in events:
        if ev['kind'] in _SEVERITY and 'layer' in ev:
            first_bad = {'layer': ev['layer'], 'step': ev['step'],
                         'kind': ev['kind'], 'detail': ev['detail']}
            break

    return {
        'n_records': len(records),
        'steps': [min(steps), max(steps)] if steps else None,
        'gaps': gaps,
        'events': events,
        'first_bad_layer': first_bad,
    }


def analyze_compile_journal(
    records: list[dict[str, Any]],
) -> dict[str, Any]:
    """Triage a compile-watch heartbeat journal.

    Each compilation journals ``lowering -> compiling -> done`` records
    (fsynced before the blocking phase they announce), so the last
    heartbeat of a killed process is trustworthy. Returns::

        {'entries': {entry: {'compiles': N, 'total_compile_s': S}},
         'in_flight': [{'entry', 'phase', 'elapsed_s', ...}...],
         'verdict': 'died compiling ...' | None}

    ``in_flight`` lists compilations that never reached ``done`` —
    normally empty; after a mid-compile death it names the culprit.
    """
    entries: dict[str, dict[str, Any]] = {}
    open_compiles: dict[tuple[Any, Any, Any], dict[str, Any]] = {}
    for rec in records:
        phase = rec.get('phase')
        entry = rec.get('entry')
        key = (rec.get('pid'), entry, rec.get('n'))
        if phase == 'lowering':
            fp = rec.get('fingerprint') or {}
            open_compiles[key] = {
                'entry': entry,
                'phase': 'lowering',
                'started_t': rec.get('t'),
                'last_t': rec.get('t'),
                'pid': rec.get('pid'),
                'n_args': len(fp),
                'diff': rec.get('diff') or [],
            }
        elif key in open_compiles:
            oc = open_compiles[key]
            oc['last_t'] = rec.get('t', oc['last_t'])
            if phase == 'done':
                agg = entries.setdefault(
                    entry, {'compiles': 0, 'total_compile_s': 0.0})
                agg['compiles'] += 1
                agg['total_compile_s'] += float(rec.get('compile_s') or 0.0)
                del open_compiles[key]
            else:
                oc['phase'] = phase
                if rec.get('lowering_s') is not None:
                    oc['lowering_s'] = rec['lowering_s']

    in_flight = []
    for oc in open_compiles.values():
        started, last = oc.get('started_t'), oc.get('last_t')
        if isinstance(started, (int, float)) and isinstance(
                last, (int, float)):
            oc['elapsed_s'] = max(0.0, float(last) - float(started))
        in_flight.append(oc)

    verdict = None
    if in_flight:
        worst = in_flight[-1]  # journal order: the last one written
        elapsed = worst.get('elapsed_s')
        after = (f' after >= {elapsed:.1f}s'
                 if isinstance(elapsed, float) else '')
        verdict = (
            f"died compiling {worst['entry']!r}{after}: last heartbeat "
            f"in phase {worst['phase']!r} never reached 'done' "
            f"({worst.get('n_args', '?')} fingerprinted arg leaves, "
            f"pid {worst.get('pid', '?')})")
    return {'entries': entries, 'in_flight': in_flight, 'verdict': verdict}


# ---------------------------------------------------------------- printing


def _print_analysis(analysis: dict[str, Any]) -> None:
    span = analysis['steps']
    span_s = f'steps {span[0]}..{span[1]}' if span else 'no steps'
    print(f"{analysis['n_records']} records, {span_s}, "
          f"{len(analysis['gaps'])} gap(s)")
    if not analysis['events']:
        print('timeline: no divergence events detected')
    else:
        print('timeline:')
        for ev in analysis['events']:
            layer = f" [{ev['layer']}]" if 'layer' in ev else ''
            print(f"  step {ev['step']:>6}  {ev['kind']}{layer}: "
                  f"{ev['detail']}")
    fb = analysis['first_bad_layer']
    if fb:
        print(f"first bad layer: {fb['layer']} — {fb['kind']} at "
              f"step {fb['step']} ({fb['detail']})")
    else:
        print('first bad layer: none (no per-layer factor/damping events)')


def _print_compile_analysis(comp: dict[str, Any]) -> None:
    entries = comp['entries']
    total = sum(e['compiles'] for e in entries.values())
    print(f"compile journal: {total} completed compilation(s) across "
          f"{len(entries)} entry(ies)")
    for name, agg in sorted(entries.items()):
        print(f"  {name}: {agg['compiles']} compile(s), "
              f"{agg['total_compile_s']:.2f}s total")
    if comp['verdict']:
        print(f"VERDICT: {comp['verdict']}")
    else:
        print('no in-flight compilations: every heartbeat reached done')


def _print_compile_events(bundle: dict[str, Any]) -> None:
    events = bundle.get('compile_events') or []
    if not events:
        return
    counts: dict[str, int] = {}
    for ev in events:
        counts[ev.get('entry', '?')] = counts.get(ev.get('entry', '?'), 0) + 1
    recompiles = sum(c - 1 for c in counts.values() if c > 1)
    print(f"  compile events: {len(events)} compile(s) over "
          f"{len(counts)} entry(ies), {recompiles} recompile(s)")
    last = events[-1]
    diff = last.get('diff') or []
    if diff:
        print(f"    last recompile ({last.get('entry')}): {diff[0]}" +
              (f' (+{len(diff) - 1} more)' if len(diff) > 1 else ''))
    memory = bundle.get('compile_memory') or {}
    for name, snap in sorted(memory.items()):
        hbm = snap.get('hbm_bytes')
        if hbm:
            print(f"    {name}: XLA memory {hbm / 1e6:.2f} MB "
                  f"(arg+out+temp)")


def _print_bundle_header(bundle: dict[str, Any]) -> None:
    man = bundle['manifest']
    print(f"postmortem bundle: {bundle['dir']}")
    print(f"  reason: {man.get('reason')}  step: {man.get('step')}  "
          f"process: {man.get('process_index')}  "
          f"schema: {man.get('schema')}")
    fp = bundle.get('fingerprint', {})
    if fp:
        mesh = fp.get('mesh')
        mesh_s = (f"  mesh {mesh['axis_names']}x{mesh['shape']}"
                  if mesh else '')
        print(f"  jax {fp.get('jax')} ({fp.get('backend')}, "
              f"{fp.get('device_count')} device(s), "
              f"{fp.get('process_count')} process(es)){mesh_s}")
    health = bundle.get('health', {})
    if health.get('enabled'):
        skipped = health.get('skipped_steps', 0)
        layers = health.get('layers', {})
        flagged = {n: e for n, e in layers.items()
                   if e.get('status') != 'ok'}
        print(f"  health: {skipped} skipped step(s), "
              f"{len(flagged)}/{len(layers)} layer(s) flagged")
        for n, e in sorted(flagged.items()):
            print(f"    {n}: {e.get('status')} "
                  f"(damping_mult={e.get('damping_mult')}, "
                  f"bad_inv={e.get('bad_inv')}, "
                  f"quarantine_events={e.get('quarantine_events')})")
    comms = bundle.get('comms')
    if comms:
        st = comms.get('stat_transport', {})
        totals = comms.get('padding_totals', {})
        print(f"  comms: stat transport {st.get('method', '?')} "
              f"{st.get('bytes', '?')} B, grad broadcast "
              f"{comms.get('grad_broadcast_bytes', '?')} B, padding fill "
              f"{totals.get('fill', '?')}")
    _print_compile_events(bundle)


# ---------------------------------------------------------------- selftest


def selftest() -> int:
    """Analysis checks against synthesized divergences (no JAX needed)."""
    base = {'kl_clip_scale': 1.0,
            'damping_eff/fc1': 0.003, 'damping_eff/fc2': 0.003,
            'factor_lmin/a/fc1': 0.1, 'factor_lmax/a/fc1': 2.0,
            'factor_lmin/g/fc1': 0.1, 'factor_lmax/g/fc1': 2.0,
            'factor_lmin/a/fc2': 0.1, 'factor_lmax/a/fc2': 2.0,
            'factor_lmin/g/fc2': 0.1, 'factor_lmax/g/fc2': 2.0}
    records = []
    for s in range(8):
        rec = dict(base, step=s, loss=1.0 / (s + 1), grad_norm=1.0)
        if s >= 4:  # fc2's A factor blows up first...
            rec['factor_lmax/a/fc2'] = 3e9
        if s >= 5:  # ...then its damping escalates...
            rec['damping_eff/fc2'] = 0.03
        if s >= 6:  # ...fc1 follows with a non-finite bound...
            rec['factor_lmax/g/fc1'] = float('inf')
        if s == 7:  # ...and finally the loss goes over
            rec['loss'] = float('nan')
        records.append(rec)
    del records[3]  # a skipped step leaves a gap

    a = analyze(records)
    assert a['n_records'] == 7 and a['steps'] == [0, 7], a
    assert a['gaps'] == [[3, 3]], a['gaps']
    fb = a['first_bad_layer']
    assert fb and fb['layer'] == 'fc2' and fb['step'] == 4, fb
    assert fb['kind'] == 'huge_factor', fb
    kinds = [(e['step'], e['kind']) for e in a['events']]
    assert (4, 'huge_factor') in kinds
    assert (5, 'damping_escalation') in kinds
    assert (6, 'nonfinite_factor') in kinds
    assert (7, 'nonfinite_loss') in kinds
    # events fire once per (kind, layer/side), not once per record
    assert sum(1 for _, k in kinds if k == 'huge_factor') == 1

    # a clean run has an empty timeline
    clean = analyze([dict(base, step=s, loss=1.0, grad_norm=1.0)
                     for s in range(4)])
    assert clean['events'] == [] and clean['first_bad_layer'] is None

    # compile journal: a completed compile plus one killed mid-compile
    # (lowering + compiling heartbeats, never done) yields the verdict
    journal = [
        {'kind': 'compile', 'phase': 'lowering', 'entry': 'kfac.step',
         'n': 1, 'pid': 41, 't': 100.0,
         'fingerprint': {'[0]': {'shape': [8, 8], 'dtype': 'float32'}}},
        {'kind': 'compile', 'phase': 'compiling', 'entry': 'kfac.step',
         'n': 1, 'pid': 41, 't': 100.5, 'lowering_s': 0.5},
        {'kind': 'compile', 'phase': 'done', 'entry': 'kfac.step',
         'n': 1, 'pid': 41, 't': 103.0, 'compile_s': 2.5},
        {'kind': 'compile', 'phase': 'lowering', 'entry': 'trainer.step',
         'n': 1, 'pid': 41, 't': 110.0,
         'fingerprint': {'[0]': {'shape': [64, 6], 'dtype': 'float32'},
                         '[1]': {'shape': [64, 4], 'dtype': 'float32'}}},
        {'kind': 'compile', 'phase': 'compiling', 'entry': 'trainer.step',
         'n': 1, 'pid': 41, 't': 112.0, 'lowering_s': 2.0},
        # SIGKILL here: no 'done' for trainer.step
    ]
    comp = analyze_compile_journal(journal)
    assert comp['entries'] == {
        'kfac.step': {'compiles': 1, 'total_compile_s': 2.5}}, comp
    assert len(comp['in_flight']) == 1, comp
    flight = comp['in_flight'][0]
    assert flight['entry'] == 'trainer.step', flight
    assert flight['phase'] == 'compiling', flight
    assert flight['elapsed_s'] == 2.0, flight
    assert comp['verdict'] and 'trainer.step' in comp['verdict']
    assert "'compiling'" in comp['verdict']
    # a clean journal (every compile reached done) has no verdict
    clean_comp = analyze_compile_journal(journal[:3])
    assert clean_comp['verdict'] is None and not clean_comp['in_flight']
    # the partitioner routes mixed files to both analyses
    c_recs, m_recs = split_compile_records(journal + records)
    assert len(c_recs) == len(journal) and len(m_recs) == len(records)

    # bundle round-trip on a synthesized minimal bundle
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        bdir = os.path.join(tmp, 'postmortem-step00000007-nonfinite')
        os.makedirs(bdir)
        with open(os.path.join(bdir, 'MANIFEST.json'), 'w') as f:
            json.dump({'schema': 1, 'reason': 'nonfinite', 'step': 7,
                       'process_index': 0, 'record': {},
                       'files': ['history.jsonl',
                                 'compile_events.jsonl']}, f)
        with open(os.path.join(bdir, 'history.jsonl'), 'w') as f:
            for rec in records:
                f.write(json.dumps(rec) + '\n')
        with open(os.path.join(bdir, 'compile_events.jsonl'), 'w') as f:
            f.write(json.dumps({
                'entry': 'kfac.step', 'n': 2, 'compile_s': 1.5,
                'diff': ['[0][0]: dim 0 32 -> 64'],
                'memory': {'argument_size_in_bytes': 1024}}) + '\n')
        bundle = load_bundle(bdir)
        a2 = analyze(bundle['history'])
        assert a2['first_bad_layer']['layer'] == 'fc2'
        assert bundle['manifest']['reason'] == 'nonfinite'
        assert bundle['compile_events'][0]['entry'] == 'kfac.step'
        assert bundle['compile_events'][0]['diff'] == [
            '[0][0]: dim 0 32 -> 64']
    # --timeline: a mixed journal (killed mid-compile) + diverging
    # metrics routes BOTH verdicts through the ledger into one report
    ledger = _load_ledger()
    led = ledger.RunLedger()
    c_recs, m_recs = split_compile_records(journal[3:5] + records)
    led.ingest('compile', c_recs)
    led.ingest('metrics', m_recs)
    report = ledger.timeline_report(led)
    assert 'died compiling trainer.step' in report['verdicts']['compile'], \
        report['verdicts']
    assert 'first bad signal' in report['verdicts']['divergence'], \
        report['verdicts']

    print('kfac_inspect selftest ok')
    return 0


# -------------------------------------------------------------------- main


def _load_ledger() -> Any:
    """Load the stdlib-only ledger module from its file, bypassing the
    package ``__init__`` (which imports jax)."""
    import importlib.util
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'kfac_tpu', 'observability', 'ledger.py')
    spec = importlib.util.spec_from_file_location('_kfac_ledger', path)
    assert spec is not None and spec.loader is not None
    module = importlib.util.module_from_spec(spec)
    sys.modules['_kfac_ledger'] = module
    spec.loader.exec_module(module)
    return module


def timeline(path: str, as_json: bool = False) -> int:
    """One correlated report over a run directory or a mixed JSONL:
    compile verdicts and divergence verdicts from the same run, joined
    by the ledger instead of two separate CLI invocations."""
    ledger = _load_ledger()
    led = ledger.RunLedger()
    if os.path.isdir(path):
        if not led.ingest_dir(path):
            print(f'error: no recognizable stream files under {path}',
                  file=sys.stderr)
            return 2
    else:
        records = load_jsonl(path)
        compile_recs, metric_recs = split_compile_records(records)
        if compile_recs:
            led.ingest('compile', compile_recs)
        if metric_recs:
            led.ingest('metrics', metric_recs)
        led.assign_steps()
    if as_json:
        json.dump(ledger.timeline_report(led), sys.stdout, indent=2,
                  sort_keys=True)
        print()
    else:
        sys.stdout.write(ledger.render_timeline(led))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    parser.add_argument('path', nargs='?',
                        help='metrics JSONL file or postmortem bundle dir')
    parser.add_argument('--timeline', action='store_true',
                        help='render PATH (run dir or mixed JSONL) as a '
                             'correlated cross-stream anomaly timeline')
    parser.add_argument('--json', action='store_true',
                        help='emit the analysis as JSON instead of text')
    parser.add_argument('--selftest', action='store_true',
                        help='run the built-in analysis checks and exit')
    args = parser.parse_args(argv)

    if args.selftest:
        return selftest()
    if not args.path:
        parser.error('PATH required (or --selftest)')
    if args.timeline:
        return timeline(args.path, as_json=args.json)

    bundle = None
    if os.path.isdir(args.path):
        if not os.path.exists(os.path.join(args.path, 'MANIFEST.json')):
            print(f'error: {args.path} is a directory without '
                  'MANIFEST.json — not a postmortem bundle',
                  file=sys.stderr)
            return 2
        bundle = load_bundle(args.path)
        records = bundle['history']
    else:
        records = load_jsonl(args.path)

    compile_recs, metric_recs = split_compile_records(records)
    compile_analysis = (
        analyze_compile_journal(compile_recs) if compile_recs else None)
    analysis = analyze(metric_recs)
    if args.json:
        out = dict(analysis)
        if compile_analysis is not None:
            out['compile'] = compile_analysis
        if bundle is not None:
            out['manifest'] = bundle['manifest']
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0
    if bundle is not None:
        _print_bundle_header(bundle)
    if compile_analysis is not None:
        _print_compile_analysis(compile_analysis)
    if metric_recs or compile_analysis is None:
        _print_analysis(analysis)
    return 0


if __name__ == '__main__':
    sys.exit(main())
