"""Shared bootstrap for the ``tools/`` scripts.

Every CLI in this directory needs the same three lines of ceremony: pin
JAX to the CPU host platform (the scripts run on login nodes and in CI),
put the repo root on ``sys.path`` (the repo is not pip-installed), and
resolve paths relative to the repo root regardless of the caller's cwd.
The four original ``lint_*`` scripts each carried their own copy of this
block; they now share this one.

Usable both as a module (``import _common`` works when the script is run
as ``python tools/<script>.py`` — the tools dir is ``sys.path[0]``) and
via ``importlib`` for callers loading scripts by path.
"""

from __future__ import annotations

import os
import sys


def repo_root() -> str:
    """Absolute path of the repository root (the parent of ``tools/``)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def bootstrap(chdir: bool = False) -> str:
    """Standard script setup; returns the repo root.

    - defaults ``JAX_PLATFORMS=cpu`` (never grab the TPU tunnel from a
      lint/CLI process),
    - prepends the repo root to ``sys.path`` so ``import kfac_tpu`` works
      without installation,
    - optionally chdirs to the root for scripts that use relative paths.
    """
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    root = repo_root()
    if root not in sys.path:
        sys.path.insert(0, root)
    if chdir:
        os.chdir(root)
    return root
