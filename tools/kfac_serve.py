#!/usr/bin/env python
"""Posterior serving CLI: selftest and latency bench for the serving tier.

Drives :class:`kfac_tpu.serving.ServingEngine` — the jitted batched
uncertainty-inference engine over a Laplace export (docs/SERVING.md) —
against a toy last-layer posterior built in-process.

Usage:

    python tools/kfac_serve.py --selftest
        End-to-end sanity pass: toy export -> engine -> warmup, bucketed
        MC/closed-form parity against the direct posterior calls across
        padding buckets, routing/escalation semantics, and the
        zero-recompiles steady-state pin. Exits 0 on success (seconds,
        runs in CI — `make serve`).

    python tools/kfac_serve.py --bench
        The bench.py serving probe standalone: per-bucket p50/p95
        latency + requests/s on both paths and the cold-vs-warm AOT
        warmup A/B over a fresh persistent compile cache, printed as a
        table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: E402

_common.bootstrap()


def _toy_engine(threshold: float | None = None):
    """A trained toy classifier, its last-layer export, and an engine."""
    import jax
    import jax.numpy as jnp

    import kfac_tpu
    from kfac_tpu import health as health_lib
    from kfac_tpu.models import MLP
    from kfac_tpu.serving import ServingConfig, ServingEngine

    m = MLP(features=(8,), num_classes=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 6))
    y = jax.random.randint(jax.random.PRNGKey(2), (64,), 0, 4)
    params = m.init(jax.random.PRNGKey(0), x)['params']
    reg = kfac_tpu.register_model(m, x)
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, health=health_lib.HealthConfig(warn=False))

    def loss_fn(p, b):
        xx, yy = b
        logits = m.apply({'params': p}, xx)
        onehot = jax.nn.one_hot(yy, 4)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))

    cap = kfac_tpu.CurvatureCapture(reg)
    _, grads, stats = cap.value_stats_and_grad(loss_fn)(params, (x, y))
    state = kfac.update_factors(kfac.init(), stats)
    post_dir = tempfile.mkdtemp(prefix='kfac_serve_post_')
    kfac_tpu.export_posterior(
        kfac, state, params, post_dir,
        config=kfac_tpu.laplace.LaplaceConfig(mode='last_layer'),
        overwrite=True,
    )
    post = kfac_tpu.load_posterior(post_dir)

    def apply_fn(p, xx):
        return m.apply({'params': p}, xx)

    def phi_fn(p, xx):
        h = xx.reshape(xx.shape[0], -1)
        return jax.nn.relu(h @ p['dense0']['kernel'] + p['dense0']['bias'])

    eng = ServingEngine(
        post, apply_fn, phi_fn=phi_fn,
        config=ServingConfig(
            bucket_granularity=8, max_batch=32, n_samples=4,
            escalated_n_samples=16, variance_threshold=threshold,
            warmup_batches=(8, 32),
        ),
    )
    return post, apply_fn, phi_fn, x, eng


def selftest() -> int:
    """End-to-end checks of the bucketed engine against the posterior."""
    import jax
    import numpy as np

    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        (failures.append(what) if not cond else None)
        print(f'  {"ok " if cond else "FAIL"} {what}')

    post, apply_fn, phi_fn, x, eng = _toy_engine()
    key = jax.random.PRNGKey(7)
    warm = eng.warmup(x_spec=x[:1], key=key)
    check(warm['buckets'] == [8, 32], 'warmup compiles the config buckets')

    # bucketed MC parity vs the direct (unbucketed) posterior formula,
    # across batch sizes that pad, fill, and chunk the buckets
    def ref_mc(xx, k, n):
        keys = jax.random.split(k, n)

        def one(kk):
            return jax.nn.softmax(apply_fn(post.sample_params(kk), xx))

        return jax.vmap(one)(keys).mean(0)

    for b in (3, 8, 13, 32, 50):
        got = np.asarray(eng.mc_probs(x[:b], key, n_samples=4))
        ref = np.asarray(jax.jit(ref_mc, static_argnums=2)(x[:b], key, 4))
        check(
            np.allclose(got, ref, rtol=1e-6, atol=1e-7),
            f'MC parity vs direct posterior at batch {b} '
            f'(maxdiff {np.abs(got - ref).max():.2e})',
        )

    # closed-form parity vs the posterior's own linearized variance
    probs, var = eng.closed_form(x[:13])
    ref_probs = np.asarray(jax.nn.softmax(apply_fn(post.params, x[:13])))
    ref_var = np.asarray(post.linearized_variance(phi_fn(post.params, x[:13])))
    check(
        np.allclose(np.asarray(probs), ref_probs, rtol=1e-6),
        'closed-form probs match the MAP apply',
    )
    check(
        np.allclose(np.asarray(var), ref_var, rtol=1e-6, atol=1e-7),
        f'closed-form variance matches linearized_variance '
        f'(maxdiff {np.abs(np.asarray(var) - ref_var).max():.2e})',
    )

    # steady state: every served size above hit a warmed bucket
    check(
        eng.recompiles_after_warmup() == 0,
        'recompiles_after_warmup == 0 across all served sizes',
    )
    eng.close()

    # routing: a threshold at the median escalates some rows, answers
    # keep their shape, and escalated rows carry the MC answer
    _, _, _, x2, eng2 = _toy_engine(threshold=1e-9)  # everything escalates
    eng2.warmup(x_spec=x2[:1], key=key)
    res = eng2.serve(x2[:8], key=key, path='auto')
    mc = np.asarray(eng2.mc_probs(x2[:8], key, n_samples=16))
    check(bool(np.asarray(res.escalated).all()),
          'tiny threshold escalates every row')
    check(
        np.allclose(np.asarray(res.probs), mc, rtol=1e-6),
        'escalated rows carry the escalated-MC answer',
    )
    check(eng2.recompiles_after_warmup() == 0,
          'routing path stays at zero recompiles')
    eng2.close()

    if failures:
        print(f'kfac_serve selftest: {len(failures)} FAILURES')
        return 1
    print('kfac_serve selftest: ok')
    return 0


def bench() -> int:
    """Standalone run of the bench.py serving probe, as a table."""
    import bench as bench_lib

    out = bench_lib._serving_probe()
    print(json.dumps({k: v for k, v in out.items() if k != 'shapes'},
                     indent=2, default=str))
    print()
    print(f'{"path.bucket":<18}{"batch":>6}{"p50 ms":>9}{"p95 ms":>9}'
          f'{"req/s":>12}')
    for name, row in out['shapes'].items():
        print(f'{name:<18}{row["batch"]:>6}{row["p50_ms"]:>9}'
              f'{row["p95_ms"]:>9}{row["requests_per_sec"]:>12}')
    cold, warm = out['warmup_cold'], out['warmup_warm']
    print(
        f'\nwarmup: cold {cold["seconds"]}s '
        f'({cold["persistent_cache"]["misses"]} cache misses) -> '
        f'warm {warm["seconds"]}s '
        f'({warm["persistent_cache"]["hits"]} cache hits); '
        f'recompiles after warmup: {out["recompiles_after_warmup"]}'
    )
    return 0 if out['warm_faster'] and not out['recompiles_after_warmup'] \
        else 1


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument('--selftest', action='store_true',
                   help='end-to-end parity + recompile pin (exit 0 on ok)')
    g.add_argument('--bench', action='store_true',
                   help='per-bucket latency table + cold/warm warmup A/B')
    args = p.parse_args(argv)
    return selftest() if args.selftest else bench()


if __name__ == '__main__':
    sys.exit(main())
