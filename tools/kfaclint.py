#!/usr/bin/env python
"""kfaclint: the repo's unified static-analysis / lint entry point.

Runs the AST rules (KFL001–KFL005: host-sync-in-jit, rank-divergent
I/O, ephemeral-pytree drift, recompile hazards, callback discipline)
over ``kfac_tpu/``; with ``--ir`` the jaxpr-level IR rules
(KFL201–KFL205: dtype drift, collective axes, sharding contracts,
step-path callbacks, cost-model parity — these trace the real engines,
so they want the 8-device CPU env the Makefile sets); with ``--pod``
the cross-rank SPMD protocol rules (KFL301–KFL305: collective order
divergence, conditional collectives, rank-divergent launches, the
cross-function write-race happens-before check, protocol-table model
checking — stdlib-only, like the AST tier); and with ``--all``
everything, including the docs-vs-code drift rules (KFL100–KFL105) that
the four ``tools/lint_*.py`` wrappers delegate to. See docs/ANALYSIS.md
for the rule table and suppression syntax.

    JAX_PLATFORMS=cpu python tools/kfaclint.py --all        # CI entry
    python tools/kfaclint.py --ir --smoke                   # fast IR tier
    python tools/kfaclint.py --pod                          # pod tier
    python tools/kfaclint.py --rules KFL002 kfac_tpu/checkpoint.py
    python tools/kfaclint.py --baseline-remap old.py:new.py --all
    python tools/kfaclint.py --list-rules
    python tools/kfaclint.py --selftest

Exit codes: 0 clean (or only-baselined), 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: E402

BASELINE_DEFAULT = os.path.join(_common.repo_root(), 'tools',
                                'kfaclint_baseline.json')


# ---------------------------------------------------------------- selftest
#
# Small end-to-end fixtures, one true positive and one clean negative per
# AST rule, run through the real load_project/analyze pipeline in a temp
# dir. tests/test_kfaclint.py holds the richer suite; this is the
# no-pytest smoke check the Makefile runs (kfac_inspect.py convention).

_FIXTURES: dict[str, tuple[str, str]] = {
    'KFL001': (
        # TP: float() on a traced param inside a scoped entry point
        '''
from kfac_tpu import tracing

@tracing.scope('k.step')
def step(state, grads):
    return float(grads) + 1.0
''',
        # negative: same sync, but host-side (no scope/jit decorator)
        '''
def drain(grads):
    return float(grads)
''',
    ),
    'KFL002': (
        '''
import os
import jax

def commit(path):
    if jax.process_index() != 0:
        return
    os.replace(path + '.tmp', path)
''',
        '''
import os
import jax
from kfac_tpu.parallel import multihost

def commit(path):
    if jax.process_index() != 0:
        return
    os.replace(path + '.tmp', path)
    multihost.barrier('commit')
''',
    ),
    'KFL003': (
        '''
import jax

@jax.tree_util.register_pytree_node_class
class S:
    def __init__(self, names, a, b):
        self.names = names
        self.a = a
        self.b = b

    def tree_flatten(self):
        return ((self.b, self.a), (self.names,))

    @classmethod
    def tree_unflatten(cls, aux, children):
        (names,) = aux
        return cls(names, *children)
''',
        '''
import jax

@jax.tree_util.register_pytree_node_class
class S:
    def __init__(self, names, a, b):
        self.names = names
        self.a = a
        self.b = b

    def tree_flatten(self):
        return ((self.a, self.b), (self.names,))

    @classmethod
    def tree_unflatten(cls, aux, children):
        (names,) = aux
        return cls(names, *children)
''',
    ),
    'KFL004': (
        '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=('cfg',))
def step(x, cfg: dict):
    if x:
        return x
    return x
''',
        '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=('flag',))
def step(x, flag):
    if flag:
        return x + 1
    return x
''',
    ),
    'KFL005': (
        '''
from jax.experimental import io_callback

def launch(cb, x):
    return io_callback(cb, None, x)
''',
        '''
from jax.experimental import io_callback

def launch(cb, x):
    return io_callback(cb, None, x, ordered=False)
''',
    ),
    'KFL301': (
        # TP: arms of a rank branch reorder the same collectives
        '''
from kfac_tpu.parallel import multihost

def sync(x):
    if multihost.process_index() == 0:
        multihost.barrier('a')
        vals = multihost.allgather_scalars(x)
    else:
        vals = multihost.allgather_scalars(x)
        multihost.barrier('a')
    return vals
''',
        '''
from kfac_tpu.parallel import multihost

def sync(x):
    if multihost.process_index() == 0:
        prepare(x)
    multihost.barrier('a')
    return multihost.allgather_scalars(x)
''',
    ),
    'KFL302': (
        # TP: only rank 0 enters the unanimous vote — peers never arrive
        '''
from kfac_tpu.parallel import multihost

def migrate(ok):
    if multihost.process_index() == 0:
        ok = multihost.agree_decision(ok)
    return ok
''',
        '''
from kfac_tpu.parallel import multihost

def migrate(ok):
    return multihost.agree_decision(ok)
''',
    ),
    'KFL303': (
        # TP: process_index()-derived operand feeds a jitted entry
        '''
import jax

@jax.jit
def step(x):
    return x * 2

def drive(x):
    pidx = jax.process_index()
    return step(x[: pidx + 1])
''',
        '''
import jax

@jax.jit
def step(x):
    return x * 2

def drive(x):
    return step(x)
''',
    ),
    'KFL304': (
        # TP: the manager-save shape with its barrier doctored out —
        # the rank-0 rmtree hides inside a retry lambda, and no calling
        # context reaches an ordering op
        '''
import os
import shutil
from kfac_tpu.parallel import multihost

def _with_retries(what, fn):
    return fn()

def save(state, sdir):
    if multihost.process_index() == 0 and os.path.exists(sdir):
        _with_retries('clearing stale dir', lambda: shutil.rmtree(sdir))
    write(state, sdir)
''',
        '''
import os
import shutil
from kfac_tpu.parallel import multihost

def _with_retries(what, fn):
    return fn()

def save(state, sdir):
    if multihost.process_index() == 0 and os.path.exists(sdir):
        _with_retries('clearing stale dir', lambda: shutil.rmtree(sdir))
    multihost.barrier('save')
    write(state, sdir)
''',
    ),
    'KFL305': (
        # TP: declared save sequence lost its barrier and its wait
        '''
SAVE_PROTOCOL = {
    'machine': 'sequence',
    'name': 'save',
    'function': 'save',
    'steps': (
        {'op': 'clear', 'rank': 0, 'kind': 'mutate',
         'effect': 'mutate_dir'},
        {'op': 'write', 'rank': 'all', 'kind': 'mutate',
         'effect': 'write_step_dir'},
        {'op': 'commit', 'rank': 0, 'kind': 'mutate',
         'effect': 'point_latest'},
    ),
}

def save():
    pass
''',
        '''
from kfac_tpu.parallel import multihost

SAVE_PROTOCOL = {
    'machine': 'sequence',
    'name': 'save',
    'function': 'save',
    'steps': (
        {'op': 'clear', 'rank': 0, 'kind': 'mutate',
         'effect': 'mutate_dir'},
        {'op': 'barrier', 'rank': 'all', 'kind': 'barrier'},
        {'op': 'write', 'rank': 'all', 'kind': 'mutate',
         'effect': 'write_step_dir'},
        {'op': 'wait', 'rank': 'all', 'kind': 'wait'},
        {'op': 'commit', 'rank': 0, 'kind': 'mutate',
         'effect': 'point_latest'},
    ),
}

def save(ckptr):
    multihost.barrier('save')
    ckptr.wait_until_finished()
''',
    ),
}


def _run_fixture(analysis, tmp: str, source: str, codes: list[str]):
    path = os.path.join(tmp, 'mod.py')
    with open(path, 'w', encoding='utf-8') as f:
        f.write(source)
    project, errs = analysis.load_project(tmp)
    return analysis.analyze(
        project, analysis.get_rules(codes), parse_errors=errs
    )


def selftest() -> int:
    import tempfile

    from kfac_tpu import analysis

    for code, (positive, negative) in sorted(_FIXTURES.items()):
        with tempfile.TemporaryDirectory() as tmp:
            hits = _run_fixture(analysis, tmp, positive, [code])
            assert any(f.code == code for f in hits), (
                f'{code}: true-positive fixture produced no finding'
            )
        with tempfile.TemporaryDirectory() as tmp:
            hits = _run_fixture(analysis, tmp, negative, [code])
            assert not hits, (
                f'{code}: clean fixture flagged: '
                + '; '.join(f.render() for f in hits)
            )

    # suppression with a reason silences; without one becomes KFL000
    with tempfile.TemporaryDirectory() as tmp:
        tp = _FIXTURES['KFL005'][0].replace(
            'return io_callback(cb, None, x)',
            'return io_callback(cb, None, x)  '
            '# kfaclint: disable=KFL005 (fixture: ordering irrelevant)',
        )
        assert not _run_fixture(analysis, tmp, tp, ['KFL005'])
    with tempfile.TemporaryDirectory() as tmp:
        tp = _FIXTURES['KFL005'][0].replace(
            'return io_callback(cb, None, x)',
            'return io_callback(cb, None, x)  # kfaclint: disable=KFL005',
        )
        hits = _run_fixture(analysis, tmp, tp, ['KFL005'])
        assert any(f.code == 'KFL000' for f in hits), hits

    # baseline round-trip
    with tempfile.TemporaryDirectory() as tmp:
        findings = _run_fixture(
            analysis, tmp, _FIXTURES['KFL002'][0], ['KFL002']
        )
        bpath = os.path.join(tmp, 'baseline.json')
        analysis.save_baseline(bpath, findings)
        new, matched = analysis.split_baseline(
            findings, analysis.load_baseline(bpath)
        )
        assert not new and matched == len(findings)

    # JSON reporter schema
    payload = json.loads(analysis.render_json([], baselined=0, checked=3))
    assert payload['schema'] == 1 and payload['tool'] == 'kfaclint'
    assert payload['summary']['files_checked'] == 3

    print('kfaclint selftest ok: '
          f'{len(_FIXTURES)} rule fixtures, suppressions, baseline, json')
    return 0


# -------------------------------------------------------------------- main


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    parser.add_argument('targets', nargs='*',
                        help='files/dirs to analyze (default: kfac_tpu/)')
    parser.add_argument('--all', action='store_true',
                        help='run every registered rule: AST, project '
                             'drift (KFL100-KFL105) and IR (KFL201-KFL205)')
    parser.add_argument('--ir', action='store_true',
                        help='run the IR rules (KFL201-KFL205): trace '
                             'engine entry points to jaxprs and check the '
                             'lowered program')
    parser.add_argument('--pod', action='store_true',
                        help='run the pod rules (KFL301-KFL305): '
                             'abstractly interpret host control code '
                             'across virtual ranks and model-check the '
                             'coordination protocol')
    parser.add_argument('--smoke', action='store_true',
                        help='with --ir/--all: trace only the dense d=64 '
                             'eigen config (bounded wall-clock; the full '
                             'matrix lives behind the slow test marker)')
    parser.add_argument('--rules',
                        help='comma-separated rule codes to run '
                             '(default: all AST rules)')
    parser.add_argument('--baseline-remap', action='append', default=[],
                        metavar='OLD:NEW',
                        help='rewrite baseline paths OLD->NEW before '
                             'matching (repeatable; OLD ending in / '
                             'remaps a directory prefix) — for git mv')
    parser.add_argument('--json', action='store_true',
                        help='emit the report as JSON instead of text')
    parser.add_argument('--baseline', default=BASELINE_DEFAULT,
                        help='baseline file (default: '
                             'tools/kfaclint_baseline.json)')
    parser.add_argument('--update-baseline', action='store_true',
                        help='rewrite the baseline to the current '
                             'findings and exit 0')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the rule registry and exit')
    parser.add_argument('--selftest', action='store_true',
                        help='run the built-in rule fixtures and exit')
    args = parser.parse_args(argv)

    root = _common.bootstrap()
    if args.selftest:
        return selftest()

    from kfac_tpu import analysis

    if args.list_rules:
        for rule in analysis.all_rules():
            print(f'{rule.code}  [{rule.kind:>7}]  {rule.name}')
            print(f'        {rule.what}')
        return 0

    if args.smoke or args.ir or args.all:
        from kfac_tpu.analysis import ir as ir_lib

        ir_lib.set_profile('smoke' if args.smoke else 'default')

    try:
        if args.rules:
            rules = analysis.get_rules(args.rules.split(','))
        elif args.all:
            rules = analysis.all_rules()
        elif args.ir or args.pod:
            codes = ()
            if args.ir:
                codes += analysis.IR_RULE_CODES
            if args.pod:
                codes += analysis.POD_RULE_CODES
            rules = analysis.get_rules(codes)
        else:
            rules = analysis.get_rules(analysis.AST_RULE_CODES)
    except KeyError as exc:
        print(exc.args[0], file=sys.stderr)
        return 2

    targets = args.targets or ['kfac_tpu']
    project, parse_errors = analysis.load_project(root, targets)
    findings = analysis.analyze(project, rules, parse_errors=parse_errors)

    if args.update_baseline:
        analysis.save_baseline(args.baseline, findings)
        print(f'baseline updated: {len(findings)} finding(s) -> '
              f'{args.baseline}')
        return 0

    baseline = analysis.load_baseline(args.baseline)
    if args.baseline_remap:
        renames = {}
        for item in args.baseline_remap:
            old, sep, new = item.partition(':')
            if not sep or not old or not new:
                print(f'--baseline-remap wants OLD:NEW, got {item!r}',
                      file=sys.stderr)
                return 2
            renames[old] = new
        baseline = analysis.remap_baseline(baseline, renames)
    new, matched = analysis.split_baseline(findings, baseline)
    render = analysis.render_json if args.json else analysis.render_text
    print(render(new, baselined=matched, checked=len(project.modules)))
    return 1 if new else 0


if __name__ == '__main__':
    sys.exit(main())
