"""Build a tokenized memmap corpus from raw text for the LM trainer.

Offline counterpart of the reference's torchtext PTB/WikiText pipeline
(examples/language/dataset.py builds a frequency vocab over the train
split and maps lines to id tensors): word-level tokens, most-frequent
``--vocab-size - 2`` words kept, ``<unk>`` id 0 for the tail and
``<eos>`` id 1 appended per line (the reference appends <eos> the same
way). Output layout consumed by ``examples/data.lm_corpus``:

    <out-dir>/corpus.npy   int32 token ids (memory-mapped by the trainer)
    <out-dir>/vocab.json   {"size": N, "itos": [...]}

Usage: python tools/tokenize_corpus.py INPUT.txt --out-dir DATA_DIR
       python examples/train_language_model.py --data-dir DATA_DIR ...

Tokenization streams the file twice (count pass + encode pass) and
accumulates ids in bounded chunks, so corpora far larger than RAM work;
only the final id array write is O(corpus) on disk.
"""

from __future__ import annotations

import argparse
import collections
import json
import os

import numpy as np

UNK, EOS = 0, 1


def _lines(path: str, lower: bool):
    with open(path, encoding='utf-8', errors='replace') as f:
        for line in f:
            yield (line.lower() if lower else line).split()


def build_vocab(
    text_path: str, vocab_size: int, lower: bool = True
) -> tuple[list[str], int]:
    """(itos, total_tokens): <unk>, <eos>, then words by descending
    frequency. The token count (words + one <eos> per line) sizes the
    output memmap so the encode pass never holds the corpus in RAM."""
    counts: collections.Counter[str] = collections.Counter()
    n_tokens = 0
    for words in _lines(text_path, lower):
        counts.update(words)
        n_tokens += len(words) + 1  # + <eos>
    keep = [w for w, _ in counts.most_common(max(0, vocab_size - 2))]
    return ['<unk>', '<eos>'] + keep, n_tokens


def encode_to_npy(
    text_path: str,
    out_path: str,
    itos: list[str],
    n_tokens: int,
    lower: bool = True,
) -> None:
    """Stream token ids straight into ``out_path`` (.npy): peak memory is
    one ~4 MB chunk regardless of corpus size."""
    stoi = {w: i for i, w in enumerate(itos)}
    out = np.lib.format.open_memmap(
        out_path, mode='w+', dtype=np.int32, shape=(n_tokens,)
    )
    pos = 0
    buf: list[int] = []

    def flush():
        nonlocal pos
        if buf:
            out[pos : pos + len(buf)] = np.asarray(buf, np.int32)
            pos += len(buf)
            buf.clear()

    for words in _lines(text_path, lower):
        buf.extend(stoi.get(w, UNK) for w in words)
        buf.append(EOS)
        if len(buf) >= 1 << 20:
            flush()
    flush()
    assert pos == n_tokens, (pos, n_tokens)
    out.flush()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument('text', help='raw text file (one or more sentences/line)')
    p.add_argument('--out-dir', required=True)
    p.add_argument('--vocab-size', type=int, default=8192)
    p.add_argument(
        '--no-lower', action='store_true',
        help='keep case (default lowercases, as the reference PTB pipeline)',
    )
    args = p.parse_args(argv)

    lower = not args.no_lower
    itos, n_tokens = build_vocab(args.text, args.vocab_size, lower)
    if len(itos) <= 2:  # only the specials: no actual words were seen
        raise SystemExit(
            f'{args.text!r} contains no tokens; refusing to write an '
            'empty corpus (the trainer would fail with opaque errors)'
        )
    os.makedirs(args.out_dir, exist_ok=True)
    out_path = os.path.join(args.out_dir, 'corpus.npy')
    encode_to_npy(args.text, out_path, itos, n_tokens, lower)
    with open(os.path.join(args.out_dir, 'vocab.json'), 'w') as f:
        # max_token lets lm_corpus validate size > max(token id) in O(1)
        # instead of scanning the memmap (ids are 0..size-1 by
        # construction here, so the pair is consistent forever)
        json.dump(
            {'size': len(itos), 'itos': itos, 'max_token': len(itos) - 1}, f
        )
    print(f'{n_tokens} tokens, vocab {len(itos)} -> {out_path}')


if __name__ == '__main__':
    main()
