"""Time-to-target-quality: K-FAC vs the same first-order baseline.

This measures the metric BASELINE.json actually names — "steps/sec AND
time-to-target-acc vs SGD" — as a curve, generalizing the reference's
boolean MNIST gate (tests/integration/mnist_integration_test.py:104-176:
KFAC accuracy strictly greater after equal epochs) the way its papers
report results (KAISA: time-to-convergence reductions).

Four tasks, all on real offline data (no network egress in this env):

- ``digits_mlp``:     sklearn digits, 1-hidden-layer MLP (dense K-FAC path)
- ``digits_cnn``:     sklearn digits as 8x8 images, small ConvNet (conv
                      K-FAC path — conv_general_dilated_patches factors)
- ``char_lm``:        byte-level Transformer LM (2 layers, d64, seq 64)
                      over this repo's own docs (a real text corpus that
                      ships with the repo); the quality metric is held-out
                      cross-entropy (lower=better)
- ``char_lm_deep``:   4 layers, d128, seq 128, longer horizon — note its
                      shared lr is 0.1 because at 0.3 plain SGD DIVERGES
                      on this depth while K-FAC's kl-clip keeps it stable
                      (a run that diverges is reported as such and never
                      counts as reaching the target)

Protocol per task: train SGD(+momentum) and the SAME optimizer wrapped
with the K-FAC preconditioner, identical lr/batch/init, evaluating every
``eval_every`` steps. The target is self-calibrating: the WORSE of the two
final qualities (both runs reached it), so no hand-tuned threshold can
favor either side. Reported: steps and wall-seconds to target (compile
time excluded via warmup; per-step K-FAC overhead therefore shows up
honestly in the seconds column), plus the full curves.

Usage:
    python tools/bench_accuracy.py [--out BENCH_ACC.md] [--tasks ...]

Writes a markdown report and prints one JSON line per task.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common

sys.path.insert(0, _common.repo_root())

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import kfac_tpu
from kfac_tpu import training


def _log(msg: str) -> None:
    print(f'[acc] {msg}', file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# tasks
# ---------------------------------------------------------------------------


class SmallCNN(nn.Module):
    """8x8x1 -> conv16 -> conv32 -> dense head: exercises the Conv2d
    K-FAC helper on real (if tiny) images."""

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(16, (3, 3))(x)
        x = nn.relu(x)
        x = nn.Conv(32, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(10)(x)


def _docs_corpus(max_bytes: int = 400_000) -> np.ndarray:
    """Byte tokens from the repo's own markdown/docs — real English text
    that ships offline with the repo."""
    root = _common.repo_root()
    paths = [os.path.join(root, 'README.md'), os.path.join(root, 'SURVEY.md')]
    docs_dir = os.path.join(root, 'docs')
    if os.path.isdir(docs_dir):
        paths += [
            os.path.join(docs_dir, p)
            for p in sorted(os.listdir(docs_dir))
            if p.endswith('.md')
        ]
    blob = b'\n\n'.join(
        open(p, 'rb').read() for p in paths if os.path.exists(p)
    )[:max_bytes]
    return np.frombuffer(blob, dtype=np.uint8).astype(np.int32)


def _task_digits(arch: str):
    from examples import data

    (xtr, ytr), (xte, yte) = data.digits()
    from kfac_tpu.models import MLP

    if arch == 'cnn':
        xtr = xtr.reshape(-1, 8, 8, 1)
        xte = xte.reshape(-1, 8, 8, 1)
        model = SmallCNN()
    else:
        model = MLP(features=(64,), num_classes=10)
    xtr, ytr = jnp.asarray(xtr), jnp.asarray(ytr)
    xte, yte = jnp.asarray(xte), jnp.asarray(yte)

    def loss_fn(p, ms, b):
        xx, yy = b
        logits = model.apply({'params': p}, xx)
        onehot = jax.nn.one_hot(yy, 10)
        nll = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
        return nll, ms

    @jax.jit
    def evaluate(p, ms):
        del ms
        logits = model.apply({'params': p}, xte)
        return (jnp.argmax(logits, -1) == yte).mean()

    # Per-arch shared lr: chosen so the task does NOT saturate instantly
    # (at lr 0.1 the CNN hits 99% inside 120 steps either way and the
    # curves are pure noise); damping is a K-FAC-only knob with no SGD
    # counterpart, so tuning it keeps the comparison symmetric.
    lr = 0.1 if arch == 'mlp' else 0.02
    damping = 0.003 if arch == 'mlp' else 0.01
    return dict(
        model=model, example=xtr[:8], loss_fn=loss_fn, evaluate=evaluate,
        data=(xtr, ytr), batch=100, lr=lr, higher_better=True,
        metric='test_acc', max_steps=600, eval_every=17,
        kfac_kwargs=dict(
            damping=damping, factor_update_steps=5, inv_update_steps=25
        ),
    )


def _task_char_lm(depth='small'):
    tokens = _docs_corpus()
    seq = 64 if depth == 'small' else 128
    vocab = 256
    n = (len(tokens) - 1) // seq
    x = tokens[: n * seq].reshape(n, seq)
    y = tokens[1 : n * seq + 1].reshape(n, seq)
    # held-out tail: last 10% of sequences
    n_te = max(8, n // 10)
    xtr, ytr = jnp.asarray(x[:-n_te]), jnp.asarray(y[:-n_te])
    xte, yte = jnp.asarray(x[-n_te:][:64]), jnp.asarray(y[-n_te:][:64])

    from kfac_tpu.models import TransformerLM, lm_loss

    if depth == 'small':
        model_kw = dict(d_model=64, num_heads=4, num_layers=2)
        steps, eval_every, lr = 400, 20, 0.3
    else:  # 'deep': a more realistic transformer, longer horizon.
        # Shared lr 0.1: at 0.3 plain SGD DIVERGES on this depth while
        # K-FAC (kl-clip trust region) converges — a real K-FAC
        # robustness win, but the self-calibrating-target protocol needs
        # both runs finite, so the headline uses an lr SGD survives.
        model_kw = dict(d_model=128, num_heads=4, num_layers=4)
        steps, eval_every, lr = 700, 35, 0.1
    model = TransformerLM(vocab_size=vocab, max_len=seq, **model_kw)
    lm = lm_loss(model)

    def loss_fn(p, ms, b):
        return lm(p, b), ms

    @jax.jit
    def evaluate(p, ms):
        del ms
        return lm(p, (xte, yte))

    return dict(
        model=model, example=xtr[:2], loss_fn=loss_fn, evaluate=evaluate,
        data=(xtr, ytr), batch=16, lr=lr, higher_better=False,
        metric='val_nll', max_steps=steps, eval_every=eval_every,
        register_kwargs=dict(skip_layers=['lm_head']),
        kfac_kwargs=dict(
            damping=0.003, factor_update_steps=5, inv_update_steps=25
        ),
    )


def _task_cifar_resnet20():
    """The BASELINE.json vision config (reference
    examples/torch_cifar10_resnet.py) at accuracy-harness scale: real
    CIFAR-10 when ``KFAC_TPU_DATA_DIR`` holds cifar10.npz, else the
    shape-faithful class-conditional synthetic set. BatchNorm state rides
    the Trainer's model_state."""
    from examples import data as data_lib
    from kfac_tpu.models import resnet

    data_dir = os.environ.get('KFAC_TPU_DATA_DIR') or None
    (xtr, ytr), (xte, yte) = data_lib.cifar10(
        data_dir, n_train=12800, n_test=2000
    )
    # the on-disk branch returns the FULL dataset (n_train/n_test only
    # shape the synthetic fallback): slice before normalize so the chip
    # session doesn't materialize 50k normalized images to keep 12.8k
    xtr, ytr = xtr[:12800], ytr[:12800]
    xte, yte = xte[:2000], yte[:2000]
    if data_lib.cifar_on_disk(data_dir):
        xtr = data_lib.normalize(
            xtr, data_lib.CIFAR10_MEAN, data_lib.CIFAR10_STD
        )
        xte = data_lib.normalize(
            xte, data_lib.CIFAR10_MEAN, data_lib.CIFAR10_STD
        )
    xtr, ytr = jnp.asarray(xtr), jnp.asarray(ytr)
    xte, yte = jnp.asarray(xte), jnp.asarray(yte)
    model = resnet.resnet20(num_classes=10)

    def loss_fn(p, ms, b):
        xx, yy = b
        logits, upd = model.apply(
            {'params': p, 'batch_stats': ms}, xx, train=True,
            mutable=['batch_stats'],
        )
        onehot = jax.nn.one_hot(yy, 10)
        nll = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
        return nll, upd['batch_stats']

    @jax.jit
    def evaluate(p, ms):
        logits = model.apply(
            {'params': p, 'batch_stats': ms}, xte, train=False
        )
        return (jnp.argmax(logits, -1) == yte).mean()

    # Shared lr 0.02: neither side saturates instantly (SGD at 0.05 hits
    # its final inside 80 steps) and K-FAC is stable (at damping 0.01 it
    # oscillates 0.76-0.99 on this loss surface; 0.1 holds the
    # trajectory). Honest expectation on the SYNTHETIC set: near-parity —
    # class-conditional Gaussians are an almost-linear problem with
    # little curvature pathology for K-FAC to exploit; the real-data
    # path (KFAC_TPU_DATA_DIR) is the measurement that mirrors the
    # reference's CIFAR runs.
    return dict(
        model=model, example=xtr[:8], loss_fn=loss_fn, evaluate=evaluate,
        data=(xtr, ytr), batch=128, lr=0.02, higher_better=True,
        metric='test_acc', max_steps=400, eval_every=20,
        init_kwargs=dict(train=True), register_kwargs=dict(train=False),
        kfac_kwargs=dict(
            damping=0.1, factor_update_steps=5, inv_update_steps=25
        ),
    )


TASKS = {
    'digits_mlp': lambda: _task_digits('mlp'),
    'digits_cnn': lambda: _task_digits('cnn'),
    'char_lm': _task_char_lm,
    'char_lm_deep': lambda: _task_char_lm('deep'),
    'cifar_resnet20': _task_cifar_resnet20,
}


# ---------------------------------------------------------------------------
# gates: Laplace calibration + frozen-backbone LoRA fine-tune
# ---------------------------------------------------------------------------


def _ece(probs: np.ndarray, labels: np.ndarray, n_bins: int = 15) -> float:
    """Expected calibration error: confidence-binned |acc - conf|."""
    conf = probs.max(axis=-1)
    correct = (probs.argmax(axis=-1) == labels).astype(np.float64)
    edges = np.linspace(0.0, 1.0, n_bins + 1)
    ece = 0.0
    for lo, hi in zip(edges[:-1], edges[1:]):
        m = (conf > lo) & (conf <= hi)
        if m.any():
            ece += m.mean() * abs(correct[m].mean() - conf[m].mean())
    return float(ece)


def _nll(probs: np.ndarray, labels: np.ndarray) -> float:
    p = np.clip(probs[np.arange(len(labels)), labels], 1e-12, None)
    return float(-np.mean(np.log(p)))


def run_calibration_gate(seed: int = 0) -> dict:
    """KFAC-Laplace predictive vs the MAP point estimate, same weights.

    Trains the digits MLP under K-FAC, exports the posterior
    (kfac_tpu.laplace), refits the prior precision on a held-out split,
    and scores both predictives on the test set. The gate passes when the
    Laplace predictive beats MAP on ECE AND NLL at matched accuracy
    (within 2 points) — the Ritter et al. claim the export exists to
    serve, checked on a real task end to end.
    """
    import tempfile

    from examples import data
    from kfac_tpu.models import MLP

    _log('laplace_calibration: training digits MLP under K-FAC')
    (xtr, ytr), (xte, yte) = data.digits()
    # prior-precision fitting gets its own split: the tail of train
    n_val = 200
    xval, yval = jnp.asarray(xtr[-n_val:]), jnp.asarray(ytr[-n_val:])
    xtr, ytr = jnp.asarray(xtr[:-n_val]), jnp.asarray(ytr[:-n_val])
    xte_j, yte_np = jnp.asarray(xte), np.asarray(yte)
    model = MLP(features=(64,), num_classes=10)
    params = model.init(jax.random.PRNGKey(seed), xtr[:8])['params']
    reg = kfac_tpu.register_model(model, xtr[:8])
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, lr=0.1, damping=0.003,
        factor_update_steps=5, inv_update_steps=25,
    )

    def loss_fn(p, ms, b):
        xx, yy = b
        logits = model.apply({'params': p}, xx)
        onehot = jax.nn.one_hot(yy, 10)
        return (
            -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1)),
            ms,
        )

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.1, momentum=0.9), kfac=kfac
    )
    state = trainer.init(params, None)
    bsz, n_batches = 100, len(xtr) // 100
    for i in range(300):
        j = (i % n_batches) * bsz
        state, _ = trainer.step(state, (xtr[j:j + bsz], ytr[j:j + bsz]))

    def apply_fn(p, xx):
        return model.apply({'params': p}, xx)

    key = jax.random.PRNGKey(seed + 17)
    with tempfile.TemporaryDirectory() as tmp:
        kfac_tpu.export_posterior(
            kfac, state.kfac_state, state.params, tmp, overwrite=True
        )
        post = kfac_tpu.load_posterior(tmp)
    post, nlls = kfac_tpu.fit_prior_precision(
        post, apply_fn, (xval, yval), key
    )
    _log(
        'laplace_calibration: fitted prior_precision '
        f'{post.config.prior_precision:g}'
    )

    probs_map = np.asarray(jax.nn.softmax(apply_fn(state.params, xte_j)))
    probs_lap = np.asarray(post.predictive(apply_fn, xte_j, key))
    map_acc = float((probs_map.argmax(-1) == yte_np).mean())
    lap_acc = float((probs_lap.argmax(-1) == yte_np).mean())
    out = {
        'gate': 'laplace_calibration',
        'map_acc': round(map_acc, 4),
        'laplace_acc': round(lap_acc, 4),
        'map_nll': round(_nll(probs_map, yte_np), 4),
        'laplace_nll': round(_nll(probs_lap, yte_np), 4),
        'map_ece': round(_ece(probs_map, yte_np), 4),
        'laplace_ece': round(_ece(probs_lap, yte_np), 4),
        'prior_precision': post.config.prior_precision,
        'prior_grid_nlls': {f'{k:g}': round(v, 4) for k, v in nlls.items()},
    }
    out['passed'] = bool(
        out['laplace_nll'] <= out['map_nll']
        and out['laplace_ece'] <= out['map_ece']
        and abs(lap_acc - map_acc) <= 0.02
    )
    print(json.dumps(out), flush=True)
    return out


def run_serving_routing_gate(seed: int = 0) -> dict:
    """Uncertainty-aware routing must EARN its extra samples.

    Trains the digits MLP under K-FAC (same recipe as the calibration
    gate), exports a last-layer posterior, and serves the test set
    through ``ServingEngine`` with ``path='auto'``: closed-form variance
    above the threshold (the 80th percentile of test-set variance, so
    ~20% of rows escalate) re-answers those rows with escalated MC.
    The gate passes when, on the escalated high-variance slice, the MC
    answers beat the unescalated closed-form/MAP baseline on ECE AND
    NLL at matched accuracy (within 2 points) — the measured claim
    behind the router's existence (docs/SERVING.md). Also asserts the
    bucketed engine stayed at zero steady-state recompiles.

    The serve set is the test set under Gaussian input corruption
    (sigma 0.8): clean 8x8 digits saturate — the high-variance slice is
    still 100% correct and extra samples only add entropy — so the
    measurement lives where uncertainty routing matters, the
    distribution-shift setting the Laplace literature evaluates
    (MAP confidently wrong, MC predictive honestly spread).
    """
    import tempfile

    from examples import data
    from kfac_tpu.models import MLP
    from kfac_tpu.serving import ServingConfig, ServingEngine

    _log('serving_routing: training digits MLP under K-FAC')
    (xtr, ytr), (xte, yte) = data.digits()
    n_val = 200
    xval, yval = jnp.asarray(xtr[-n_val:]), jnp.asarray(ytr[-n_val:])
    xtr, ytr = jnp.asarray(xtr[:-n_val]), jnp.asarray(ytr[:-n_val])
    xte_j, yte_np = jnp.asarray(xte), np.asarray(yte)
    model = MLP(features=(64,), num_classes=10)
    params = model.init(jax.random.PRNGKey(seed), xtr[:8])['params']
    reg = kfac_tpu.register_model(model, xtr[:8])
    kfac = kfac_tpu.KFACPreconditioner(
        registry=reg, lr=0.1, damping=0.003,
        factor_update_steps=5, inv_update_steps=25,
    )

    def loss_fn(p, ms, b):
        xx, yy = b
        logits = model.apply({'params': p}, xx)
        onehot = jax.nn.one_hot(yy, 10)
        return (
            -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1)),
            ms,
        )

    trainer = training.Trainer(
        loss_fn=loss_fn, optimizer=optax.sgd(0.1, momentum=0.9), kfac=kfac
    )
    state = trainer.init(params, None)
    bsz, n_batches = 100, len(xtr) // 100
    for i in range(300):
        j = (i % n_batches) * bsz
        state, _ = trainer.step(state, (xtr[j:j + bsz], ytr[j:j + bsz]))

    def apply_fn(p, xx):
        return model.apply({'params': p}, xx)

    def phi_fn(p, xx):
        h = xx.reshape(xx.shape[0], -1)
        return jax.nn.relu(h @ p['dense0']['kernel'] + p['dense0']['bias'])

    key = jax.random.PRNGKey(seed + 29)
    with tempfile.TemporaryDirectory() as tmp:
        kfac_tpu.export_posterior(
            kfac, state.kfac_state, state.params, tmp,
            config=kfac_tpu.laplace.LaplaceConfig(mode='last_layer'),
            overwrite=True,
        )
        post = kfac_tpu.load_posterior(tmp)
    post, _ = kfac_tpu.fit_prior_precision(post, apply_fn, (xval, yval), key)
    _log(
        'serving_routing: fitted prior_precision '
        f'{post.config.prior_precision:g}'
    )

    sigma = 0.8
    xte_shift = xte_j + sigma * jax.random.normal(
        jax.random.PRNGKey(seed + 5), xte_j.shape)

    # threshold at the 80th percentile of the closed-form max-class
    # variance: the top ~20% most-uncertain shifted rows escalate to MC
    var = np.asarray(
        post.linearized_variance(phi_fn(post.params, xte_shift)))
    thr = float(np.quantile(var.max(axis=-1), 0.8))
    eng = ServingEngine(
        post, apply_fn, phi_fn=phi_fn,
        config=ServingConfig(
            bucket_granularity=64, max_batch=512, n_samples=8,
            escalated_n_samples=32, variance_threshold=thr,
            warmup_batches=(len(xte_j),),
        ),
    )
    eng.warmup(x_spec=xte_shift[:1], key=key)
    res = eng.serve(xte_shift, key=key, path='auto')
    recompiles = eng.recompiles_after_warmup()
    eng.close()

    mask = np.asarray(res.escalated)
    probs_base = np.asarray(
        jax.nn.softmax(apply_fn(post.params, xte_shift)))
    probs_routed = np.asarray(res.probs)
    y_hi = yte_np[mask]
    base_hi, mc_hi = probs_base[mask], probs_routed[mask]
    base_acc = float((base_hi.argmax(-1) == y_hi).mean())
    mc_acc = float((mc_hi.argmax(-1) == y_hi).mean())
    out = {
        'gate': 'serving_routing',
        'shift_sigma': sigma,
        'variance_threshold': round(thr, 6),
        'n_test': int(len(yte_np)),
        'n_escalated': int(mask.sum()),
        'recompiles_after_warmup': int(recompiles),
        'baseline_acc': round(base_acc, 4),
        'escalated_acc': round(mc_acc, 4),
        'baseline_nll': round(_nll(base_hi, y_hi), 4),
        'escalated_nll': round(_nll(mc_hi, y_hi), 4),
        'baseline_ece': round(_ece(base_hi, y_hi), 4),
        'escalated_ece': round(_ece(mc_hi, y_hi), 4),
    }
    out['passed'] = bool(
        out['n_escalated'] > 0
        and recompiles == 0
        and out['escalated_nll'] <= out['baseline_nll']
        and out['escalated_ece'] <= out['baseline_ece']
        and abs(mc_acc - base_acc) <= 0.02
    )
    print(json.dumps(out), flush=True)
    return out


def run_lora_gate(seed: int = 0, loss_target: float = 0.2) -> dict:
    """Frozen-backbone LoRA fine-tune (examples/finetune_lora.py) must
    reach its loss target: the mask + LoRA-unit path trains end to end,
    not just registers."""
    from examples import finetune_lora

    _log('lora_finetune: running examples/finetune_lora.py')
    loss = finetune_lora.main(['--steps', '300', '--seed', str(seed)])
    out = {
        'gate': 'lora_finetune',
        'final_loss': round(loss, 4),
        'loss_target': loss_target,
        'passed': bool(np.isfinite(loss) and loss <= loss_target),
    }
    print(json.dumps(out), flush=True)
    return out


GATES = {
    'laplace_calibration': run_calibration_gate,
    'lora_finetune': run_lora_gate,
    'serving_routing': run_serving_routing_gate,
}


# ---------------------------------------------------------------------------
# the measured run
# ---------------------------------------------------------------------------


def _run_one(task: dict, use_kfac: bool, seed: int = 0):
    """Train to max_steps; return curve [(step, wall_s, metric), ...].

    Wall clock starts AFTER both jitted step variants and the eval are
    compiled (warmup on a scratch copy of the initial state), so the
    curves compare steady-state stepping — K-FAC's real per-step overhead
    — not XLA compile times on this 1-core container.
    """
    model = task['model']
    variables = model.init(
        jax.random.PRNGKey(seed), task['example'],
        **task.get('init_kwargs', {}),
    )
    params = variables['params']
    mstate = variables.get('batch_stats')
    reg = kfac_tpu.register_model(
        model, task['example'], **task.get('register_kwargs', {})
    )
    kfac = (
        kfac_tpu.KFACPreconditioner(
            registry=reg, lr=task['lr'],
            **task['kfac_kwargs'],
        )
        if use_kfac
        else None
    )
    trainer = training.Trainer(
        loss_fn=task['loss_fn'],
        optimizer=optax.sgd(task['lr'], momentum=0.9),
        kfac=kfac,
    )
    xtr, ytr = task['data']
    bsz = task['batch']
    n_batches = len(xtr) // bsz

    def batch_at(i):
        j = (i % n_batches) * bsz
        return (xtr[j : j + bsz], ytr[j : j + bsz])

    evaluate = task['evaluate']

    # warmup: compile the capture variant (step 0 is always a capture
    # step), the plain variant, and the eval, on a scratch state
    scratch = trainer.init(params, mstate)
    scratch, _ = trainer.step(scratch, batch_at(0))
    scratch, _ = trainer.step(scratch, batch_at(1))
    float(evaluate(scratch.params, scratch.model_state))
    del scratch
    trainer.resume(trainer.init(params, mstate))  # host cadence back to 0

    state = trainer.init(params, mstate)
    curve = []
    t0 = time.perf_counter()
    for i in range(task['max_steps']):
        state, _ = trainer.step(state, batch_at(i))
        if (i + 1) % task['eval_every'] == 0:
            jax.block_until_ready(state.params)
            wall = time.perf_counter() - t0
            te0 = time.perf_counter()
            m = float(evaluate(state.params, state.model_state))
            # eval time is excluded from the training clock
            t0 += time.perf_counter() - te0
            curve.append((i + 1, round(wall, 3), round(m, 4)))
    return curve


def _steps_to_target(curve, target, higher_better):
    for step, wall, m in curve:
        if (m >= target) if higher_better else (m <= target):
            return step, wall
    return None, None


def run_task(name: str, seed: int = 0) -> dict:
    task = TASKS[name]()
    _log(f'{name}: SGD run')
    sgd_curve = _run_one(task, use_kfac=False, seed=seed)
    # per-run persistence: a watchdog kill mid-K-FAC-run must not lose
    # the completed SGD curve (stages run under hard budgets on-chip)
    print(
        json.dumps({'task': name, 'phase': 'sgd_curve', 'curve': sgd_curve}),
        flush=True,
    )
    _log(f'{name}: K-FAC run')
    kfac_curve = _run_one(task, use_kfac=True, seed=seed)
    print(
        json.dumps(
            {'task': name, 'phase': 'kfac_curve', 'curve': kfac_curve}
        ),
        flush=True,
    )
    hb = task['higher_better']
    final_sgd, final_kfac = sgd_curve[-1][2], kfac_curve[-1][2]
    # self-calibrating target: the worse of the two finals — both reached
    # it. A DIVERGED run (NaN final) cannot set the target: fall back to
    # the finite side's final and report the diverged side as unreached.
    diverged = [
        name
        for name, v in (('sgd', final_sgd), ('kfac', final_kfac))
        if not np.isfinite(v)
    ]
    finite = [v for v in (final_sgd, final_kfac) if np.isfinite(v)]
    if len(finite) == 2:
        target = min(finite) if hb else max(finite)
    elif finite:
        target = finite[0]
    else:
        target = float('nan')
    s_steps, s_wall = _steps_to_target(sgd_curve, target, hb)
    k_steps, k_wall = _steps_to_target(kfac_curve, target, hb)
    # a diverged run never "reaches" the target, even if a pre-divergence
    # eval point happened to dip below it — the trajectory ended in NaN
    if 'sgd' in diverged:
        s_steps = s_wall = None
    if 'kfac' in diverged:
        k_steps = k_wall = None
    out = {
        'task': name,
        'metric': task['metric'],
        'target': target,
        'final_sgd': final_sgd,
        'final_kfac': final_kfac,
        'sgd_steps_to_target': s_steps,
        'sgd_seconds_to_target': s_wall,
        'kfac_steps_to_target': k_steps,
        'kfac_seconds_to_target': k_wall,
        'step_ratio': round(k_steps / s_steps, 3) if s_steps and k_steps else None,
        'time_ratio': round(k_wall / s_wall, 3) if s_wall and k_wall else None,
        'diverged': diverged,
        'sgd_curve': sgd_curve,
        'kfac_curve': kfac_curve,
    }
    print(json.dumps({k: v for k, v in out.items()
                      if not k.endswith('_curve')}), flush=True)
    return out


def write_report(
    results: list[dict],
    path: str,
    platform: str,
    gates: list[dict] | None = None,
) -> None:
    lines = [
        '# BENCH_ACC — time-to-target-quality, K-FAC vs SGD',
        '',
        f'Platform: `{platform}`. Protocol: identical model/init/lr/batch;',
        'SGD+momentum vs the same optimizer preconditioned by K-FAC;',
        'target = the worse of the two final qualities (self-calibrating,',
        'both runs reached it — a DIVERGED run is excluded from target',
        'selection, marked in its row, and never counts as reaching the',
        'target); wall-clock excludes compile and eval.',
        'Ratios < 1.0 mean K-FAC wins. Generated by',
        '`tools/bench_accuracy.py` (the curve form of the reference\'s',
        'boolean MNIST gate, mnist_integration_test.py:104-176).',
        '',
        '| task | metric | target | SGD steps | KFAC steps | step ratio |'
        ' SGD s | KFAC s | time ratio |',
        '|---|---|---|---|---|---|---|---|---|',
    ]
    for r in results:
        task = r['task']
        if r.get('diverged'):
            task += f" (DIVERGED: {', '.join(r['diverged'])})"
        lines.append(
            f"| {task} | {r['metric']} | {r['target']} "
            f"| {r['sgd_steps_to_target']} | {r['kfac_steps_to_target']} "
            f"| {r['step_ratio']} "
            f"| {r['sgd_seconds_to_target']} | {r['kfac_seconds_to_target']} "
            f"| {r['time_ratio']} |"
        )
    lines.append('')
    for r in results:
        lines.append(f"## {r['task']} curves ({r['metric']})")
        lines.append('')
        lines.append('| step | SGD s | SGD | KFAC s | KFAC |')
        lines.append('|---|---|---|---|---|')
        for (ss, sw, sm), (ks, kw, km) in zip(
            r['sgd_curve'], r['kfac_curve']
        ):
            lines.append(f'| {ss} | {sw} | {sm} | {kw} | {km} |')
        lines.append('')
    if gates:
        lines.append('## Gates (docs/LAPLACE.md)')
        lines.append('')
        lines.append('| gate | verdict | evidence |')
        lines.append('|---|---|---|')
        for g in gates:
            verdict = 'PASS' if g.get('passed') else 'FAIL'
            ev = ', '.join(
                f'{k}={v}'
                for k, v in g.items()
                if k not in ('gate', 'passed', 'prior_grid_nlls')
            )
            lines.append(f"| {g['gate']} | {verdict} | {ev} |")
        lines.append('')
    with open(path, 'w') as f:
        f.write('\n'.join(lines))
    _log(f'wrote {path}')


def main():
    p = argparse.ArgumentParser()
    p.add_argument(
        '--tasks', nargs='*', default=sorted(TASKS), choices=sorted(TASKS)
    )
    p.add_argument(
        '--gates', nargs='*', default=sorted(GATES), choices=sorted(GATES),
        help='calibration/fine-tune gates to run after the tasks '
             '(pass --gates with no names to skip)',
    )
    p.add_argument('--out', default='BENCH_ACC.md')
    p.add_argument('--seed', type=int, default=0)
    args = p.parse_args()
    dev = jax.devices()[0]
    platform = f'{dev.platform} ({getattr(dev, "device_kind", "")})'
    _log(f'platform: {platform}')
    results = [run_task(t, args.seed) for t in args.tasks]
    gates = [GATES[g](seed=args.seed) for g in args.gates]
    write_report(results, args.out, platform, gates=gates)
    if any(not g['passed'] for g in gates):
        failed = [g['gate'] for g in gates if not g['passed']]
        _log(f'GATE FAILURE: {failed}')
        sys.exit(1)


if __name__ == '__main__':
    main()
