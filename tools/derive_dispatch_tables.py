"""Derive the Pallas dispatch-threshold artifact from microbench output.

Usage:
    python tools/derive_dispatch_tables.py SWEEP.jsonl [...] --out TABLE.json
    python tools/derive_dispatch_tables.py --selftest

Reads one or more ``tools/tpu_microbench.py`` JSONL sweeps, runs the
latency-floor check on every baseline series, and writes the versioned
threshold table the gate modules (``use_pallas_for`` /
``use_flash_for``) load-or-default. Contaminated or thin evidence HOLDS
the prior thresholds and says so in the artifact's ``provenance`` —
this tool can only move a gate on clean numbers.

The committed ``kfac_tpu/ops/dispatch_thresholds.json`` was produced by
this tool from ``bench_runs/tpu_session_20260731/micro_full.jsonl``
(see its provenance block). Re-run on a fresh on-chip fori_loop sweep
to replace it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common

_common.bootstrap()

from kfac_tpu.ops import dispatch_tables


def read_jsonl(path: str) -> list[dict]:
    ops = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    ops.append(json.loads(line))
                except ValueError:
                    pass
    return ops


def selftest() -> None:
    """Synthetic derivation: a flat (contaminated) f32 sweep must hold
    the prior, a cleanly scaling sweep with a kernel win regime must
    move the threshold."""
    flat = [
        {'op': f'cov_dense_{d}_f32', 'ms': 75.0 + (d % 7)}
        for d in (256, 512, 1024, 2048)
    ] + [
        {'op': f'cov_pallas_{d}_f32', 'ms': 15.0}
        for d in (256, 512, 1024, 2048)
    ]
    t = dispatch_tables.derive_tables(flat)
    assert t['cov']['min_dim'] == dispatch_tables.DEFAULTS['cov']['min_dim']
    assert t['provenance']['contaminated'], t['provenance']
    clean = [
        {'op': f'cov_dense_{d}_f32', 'ms': 0.01 * d * d / 256}
        for d in (256, 512, 1024, 2048)
    ] + [
        {'op': f'cov_pallas_{d}_f32',
         'ms': 15.0 if d < 1024 else 0.001 * d * d / 256}
        for d in (256, 512, 1024, 2048)
    ]
    t = dispatch_tables.derive_tables(clean)
    assert t['cov']['min_dim'] == 1024, t
    assert not t['provenance']['contaminated']
    # fused step-path families: a flat (contaminated) unfused baseline
    # holds the prior, a clean sweep with a fused win suffix moves it
    flat_ns = [
        {'op': f'ns_unfused_{d}', 'ms': 50.0 + (d % 5)}
        for d in (256, 512, 1024)
    ] + [
        {'op': f'ns_fused_{d}', 'ms': 10.0} for d in (256, 512, 1024)
    ]
    t = dispatch_tables.derive_tables(flat_ns)
    assert t['ns']['min_dim'] == dispatch_tables.DEFAULTS['ns']['min_dim']
    assert 'ns_unfused' in t['provenance']['contaminated'], t['provenance']
    clean_ns = [
        {'op': f'ns_unfused_{d}', 'ms': 0.001 * d ** 3 / 256 ** 2}
        for d in (256, 512, 1024, 2048)
    ] + [
        {'op': f'ns_fused_{d}',
         'ms': 9.0 if d < 1024 else 0.0002 * d ** 3 / 256 ** 2}
        for d in (256, 512, 1024, 2048)
    ]
    t = dispatch_tables.derive_tables(clean_ns)
    assert t['ns']['min_dim'] == 1024, t
    assert 'ns' in t['provenance'].get('derived', {}), t
    print('derive_dispatch_tables selftest: ok')


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument('sweeps', nargs='*',
                   help='tpu_microbench JSONL sweep file(s)')
    p.add_argument('--out', help='artifact path (default: stdout)')
    p.add_argument('--source', help='provenance label for the sweep '
                   '(default: the input paths)')
    p.add_argument('--selftest', action='store_true')
    args = p.parse_args()
    if args.selftest:
        selftest()
        return 0
    if not args.sweeps:
        p.error('at least one sweep JSONL is required (or --selftest)')
    ops: list[dict] = []
    for path in args.sweeps:
        ops.extend(read_jsonl(path))
    table = dispatch_tables.derive_tables(ops)
    header = next((o for o in ops if 'platform' in o and 'op' not in o), {})
    table['provenance']['source'] = {
        'sweeps': args.source or [os.path.relpath(s, _common.repo_root())
                                  for s in args.sweeps],
        'records': len(ops),
        'harness_version': header.get('harness_version', 1),
        'dispatch_mode': header.get('dispatch_mode', 'legacy'),
        'platform': header.get('platform'),
        'device_kind': header.get('device_kind'),
    }
    doc = json.dumps(table, indent=2, sort_keys=True) + '\n'
    if args.out:
        with open(args.out, 'w') as f:
            f.write(doc)
        held = table['provenance'].get('held', {})
        print(f'wrote {args.out} (held: {len(held)}, '
              f'cov.min_dim={table["cov"]["min_dim"]}, '
              f'attn.min_sk_dense={table["attn"]["min_sk_dense"]})')
    else:
        print(doc, end='')
    return 0


if __name__ == '__main__':
    sys.exit(main())
