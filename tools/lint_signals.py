#!/usr/bin/env python
"""Lint: the signal table in docs/ROBUSTNESS.md matches the handlers.

Thin wrapper kept for ``make resilience`` and existing imports; the
check now lives in the kfaclint registry as rule **KFL104** (see
``kfac_tpu/analysis/drift.py`` and docs/ANALYSIS.md). Prefer:

    JAX_PLATFORMS=cpu python tools/kfaclint.py --rules KFL104
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: E402

_common.bootstrap()

from kfac_tpu.analysis import drift  # noqa: E402

DOC = drift.ROBUSTNESS_DOC


def check(doc_path: str = DOC) -> list[str]:
    """Return human-readable drift complaints (empty = in sync)."""
    return drift.check_signals(doc_path)


def main() -> int:
    problems = check()
    if problems:
        print('signal-semantics drift between code and docs:')
        for p in problems:
            print(f'  {p}')
        return 1
    print(f'signal lint ok: {len(drift.doc_signals(DOC))} documented '
          'signals match resilience.signals.HANDLED_SIGNALS')
    return 0


if __name__ == '__main__':
    sys.exit(main())
