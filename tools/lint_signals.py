#!/usr/bin/env python
"""Lint: the signal table in docs/ROBUSTNESS.md matches the handlers.

The preemption-signal semantics are a *contract* — cluster launch
scripts send SIGTERM/SIGUSR1 expecting exactly the documented behavior —
so the table under '## Signal semantics' must stay in lockstep with
:data:`kfac_tpu.resilience.signals.HANDLED_SIGNALS`. This script parses
the backticked signal names and their exit-vs-continue semantics out of
the table and fails on any drift in either direction: an undocumented
handled signal, a documented-but-unhandled one, or a row whose
exit/continue cell contradicts the handler's ``exits`` flag.

Run via ``make resilience`` (CPU-pinned) or directly:

    JAX_PLATFORMS=cpu python tools/lint_signals.py
"""

from __future__ import annotations

import os
import re
import sys

DOC = 'docs/ROBUSTNESS.md'
SECTION = '## Signal semantics'


def _doc_section(text: str) -> str:
    start = text.index(SECTION)
    rest = text[start + len(SECTION):]
    m = re.search(r'^#{1,3} ', rest, re.MULTILINE)
    return rest[: m.start()] if m else rest


def doc_signals(doc_path: str) -> dict[str, bool]:
    """{signal name: exits} parsed from the section's table rows."""
    with open(doc_path) as f:
        section = _doc_section(f.read())
    out: dict[str, bool] = {}
    for line in section.splitlines():
        line = line.strip()
        # table rows whose first cell is a `SIGXXX` token; the header and
        # separator rows never match
        if not line.startswith('| `'):
            continue
        cells = line.split('|')
        names = re.findall(r'`(SIG[A-Z0-9]+)`', cells[1])
        if not names:
            continue
        semantics = cells[2].lower()
        exits = 'exit' in semantics
        if not exits and 'continue' not in semantics:
            raise ValueError(
                f'{doc_path}: signal-table row for {names} states neither '
                f'"exit" nor "continue": {cells[2].strip()!r}'
            )
        for name in names:
            out[name] = exits
    return out


def code_signals() -> dict[str, bool]:
    from kfac_tpu.resilience import signals

    return {name: spec.exits for name, spec in signals.HANDLED_SIGNALS.items()}


def check(doc_path: str = DOC) -> list[str]:
    """Return human-readable drift complaints (empty = in sync)."""
    documented = doc_signals(doc_path)
    actual = code_signals()
    problems = []
    for name in sorted(set(actual) - set(documented)):
        problems.append(f'handled signal not documented (add to {DOC}): {name}')
    for name in sorted(set(documented) - set(actual)):
        problems.append(f'documented signal has no handler in signals.py: {name}')
    for name in sorted(set(actual) & set(documented)):
        if actual[name] != documented[name]:
            problems.append(
                f'{name}: docs say '
                f'{"exit" if documented[name] else "continue"} but '
                f'HANDLED_SIGNALS.exits={actual[name]}'
            )
    return problems


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    # the repo is not pip-installed; make `python tools/...` work from root
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    os.chdir(repo_root)
    problems = check()
    if problems:
        print('signal-semantics drift between code and docs:')
        for p in problems:
            print(f'  {p}')
        return 1
    print(f'signal lint ok: {len(doc_signals(DOC))} documented signals '
          'match resilience.signals.HANDLED_SIGNALS')
    return 0


if __name__ == '__main__':
    sys.exit(main())
