"""TPU microbenchmarks for the K-FAC hot ops: run on the real chip to pick
factor-op implementations (eigh vs Cholesky vs Newton-Schulz) and validate
the Pallas triangular covariance against XLA's dense contraction.

Usage: python tools/tpu_microbench.py [--sizes 512 2048] [--iters 20]
Prints one JSON line per measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(fn, *args, iters=20, warmup=1):
    """Time fn with an INPUT-VARYING first argument each iteration.

    The axon pool backend memoizes repeated identical computations
    (measured: an 8-deep 4096^3 matmul chain 'ran' in 0.04 ms — 30x above
    physical peak), so same-input timing loops report cache hits. Adding
    an iteration-dependent epsilon to the first argument forces real
    execution while perturbing the math negligibly.
    """
    first, rest = args[0], args[1:]
    out = None
    # 1% scale survives bf16 rounding (additive 1e-6 would round away)
    for i in range(warmup):
        out = fn(first * (1.0 + 0.01 * (i + 1)), *rest)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(iters):
        # step must exceed bf16's spacing at 1.0 (2^-7) or adjacent
        # iterations round to identical inputs and re-enable the cache
        out = fn(first * (1.0 + 0.01 * (i + 1)), *rest)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def report(name, seconds, **extra):
    print(json.dumps({'op': name, 'ms': round(seconds * 1e3, 3), **extra}),
          flush=True)


def newton_schulz_inverse(a, damping, iters=25):
    """(a + damping*I)^-1 by Newton-Schulz: X_{k+1} = X_k (2I - M X_k).

    Pure matmuls (MXU-native). Converges when ||I - M X_0|| < 1; the init
    X_0 = I/trace(M) guarantees that for SPD M since trace(M) > lambda_max.
    """
    d = a.shape[-1]
    eye = jnp.eye(d, dtype=jnp.float32)
    m = a.astype(jnp.float32) + damping * eye
    x = eye / jnp.trace(m)
    for _ in range(iters):
        x = x @ (2.0 * eye - m @ x)
    return x


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--sizes', type=int, nargs='*', default=[512, 2048])
    p.add_argument('--iters', type=int, default=20)
    p.add_argument('--rows', type=int, default=8192)
    args = p.parse_args()

    dev = jax.devices()[0]
    print(json.dumps({'platform': dev.platform,
                      'device_kind': getattr(dev, 'device_kind', '')}),
          flush=True)

    # --- clock validation: known-FLOPs matmul chain -----------------------
    n = 4096
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)

    @jax.jit
    def mm_chain(a):
        x = a
        for _ in range(8):
            x = x @ a
        return x

    t = timeit(mm_chain, a, iters=args.iters)
    flops = 8 * 2 * n**3
    report('matmul4096_bf16_chain8', t, tflops=round(flops / t / 1e12, 1))

    # --- flash attention kernel vs einsum attention (TPU only: the
    # kernel needs real Mosaic, and the einsum path at this size is
    # minutes on CPU) ------------------------------------------------------
    from kfac_tpu.models import attention as att
    from kfac_tpu.ops import pallas_attention as pa

    on_tpu = dev.platform == 'tpu'
    b, s, h, hd = (4, 2048, 4, 128) if on_tpu else (1, 256, 1, 128)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(9), 3)
    qkv = tuple(
        jax.random.normal(kx, (b, s, h, hd), jnp.bfloat16)
        for kx in (kq, kk, kv)
    )
    dense_att = jax.jit(
        lambda q, k, v: att._finish(pa.attend_partials_einsum(q, k, v, 0, 0, True))
    )
    t = timeit(dense_att, *qkv, iters=args.iters)
    report(f'attn_einsum_s{s}', t)
    if on_tpu:
        try:
            flash = jax.jit(
                lambda q, k, v: att._finish(
                    pa.flash_attention_partials(q, k, v, causal=True)
                )
            )
            t2 = timeit(flash, *qkv, iters=args.iters)
            err = float(jnp.abs(
                flash(*qkv).astype(jnp.float32)
                - dense_att(*qkv).astype(jnp.float32)
            ).max())
            report(f'attn_flash_s{s}', t2, max_err=round(err, 5),
                   speedup=round(t / t2, 2))
        except Exception as exc:  # noqa: BLE001
            report(f'attn_flash_s{s}', float('nan'),
                   error=f'{type(exc).__name__}: {exc}')

    for d in args.sizes:
        m = jax.random.normal(jax.random.PRNGKey(d), (args.rows, d),
                              jnp.float32)
        cov = (m.T @ m) / args.rows  # SPD test matrix

        f = jax.jit(lambda c: jnp.linalg.eigh(c))
        t = timeit(f, cov, iters=max(3, args.iters // 4))
        report(f'eigh_{d}', t)

        # cholesky factor + solve against identity (the INVERSE method)
        def chol_inv(c):
            l = jax.scipy.linalg.cho_factor(
                c + 0.003 * jnp.eye(d, dtype=c.dtype)
            )
            return jax.scipy.linalg.cho_solve(l, jnp.eye(d, dtype=c.dtype))

        t = timeit(jax.jit(chol_inv), cov, iters=max(3, args.iters // 4))
        report(f'cholesky_inv_{d}', t)

        # Newton-Schulz inverse: matmul-only
        ns = jax.jit(lambda c: newton_schulz_inverse(c, 0.003))
        t = timeit(ns, cov, iters=args.iters)
        x = ns(cov)
        err = float(jnp.abs(
            x @ (cov + 0.003 * jnp.eye(d)) - jnp.eye(d)
        ).max())
        report(f'newton_schulz25_{d}', t, residual_inf=round(err, 6))

        # covariance: XLA dense contraction vs Pallas triangular kernel
        for dt, tag in ((jnp.float32, 'f32'), (jnp.bfloat16, 'bf16')):
            md = m.astype(dt)
            dense = jax.jit(
                lambda a: jax.lax.dot_general(
                    a, a, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) / a.shape[0]
            )
            t = timeit(dense, md, iters=args.iters)
            report(f'cov_dense_{d}_{tag}', t)
            try:
                from kfac_tpu.ops import pallas_cov

                t = timeit(
                    jax.jit(lambda a: pallas_cov.sym_cov(a)), md,
                    iters=args.iters,
                )
                got = pallas_cov.sym_cov(md)
                want = dense(md).astype(got.dtype)
                err = float(jnp.abs(
                    got.astype(jnp.float32) - want.astype(jnp.float32)
                ).max())
                report(f'cov_pallas_{d}_{tag}', t, max_err=round(err, 5))
            except Exception as exc:  # noqa: BLE001
                report(f'cov_pallas_{d}_{tag}', float('nan'),
                       error=f'{type(exc).__name__}: {exc}')


if __name__ == '__main__':
    main()
