"""TPU microbenchmarks for the K-FAC hot ops: run on the real chip to pick
factor-op implementations (eigh vs Cholesky vs Newton-Schulz) and validate
the Pallas triangular covariance against XLA's dense contraction.

Usage: python tools/tpu_microbench.py [--sizes 512 2048] [--iters 20]
Prints one JSON line per measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common

# repo-root import only — no bootstrap(): this script must keep the real
# TPU platform, not the CPU pin the lint/CLI scripts default to
sys.path.insert(0, _common.repo_root())

import jax
import jax.numpy as jnp

#: measurement-harness version stamped on every reported line. v1 was the
#: per-iteration host dispatch loop; v2 is the one-dispatch in-jit
#: fori_loop chain (bench.py's round records carry this so a number is
#: attributable to the harness that produced it — keep bench.py's
#: _MEASUREMENT copy in sync, tests/test_measurement.py pins the pair).
HARNESS_VERSION = 2

#: env override for the dispatch mode ('fori_loop' | 'legacy'); the
#: --dispatch flag sets it for child measurements too
DISPATCH_ENV = 'KFAC_MICROBENCH_DISPATCH'


def _dispatch_mode():
    mode = os.environ.get(DISPATCH_ENV, 'fori_loop')
    return mode if mode in ('fori_loop', 'legacy') else 'fori_loop'


def _scale(tree, c):
    """Multiply every floating leaf of a pytree by c (ints pass through:
    token ids must stay valid)."""
    return jax.tree_util.tree_map(
        lambda a: a * jnp.asarray(c, a.dtype)
        if jnp.issubdtype(jnp.result_type(a), jnp.floating) else a,
        tree,
    )


def _chain(tree, out):
    """Add a zero derived from the previous output to every floating leaf,
    creating a cross-iteration data dependency. The zero sums one element
    of EVERY floating output leaf so the whole previous program — not just
    its cheapest output — must finish before the next dispatch."""
    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if jnp.issubdtype(jnp.result_type(x), jnp.floating)]
    if not leaves:
        return tree
    z = sum((jnp.ravel(x)[0] * 0.0).astype(jnp.float32) for x in leaves)
    # inject into ONE input leaf only: an executable cannot launch until
    # all input buffers are ready, so one dependency serializes the chain;
    # per-leaf adds would put O(n_leaves) extra dispatches in the timed
    # region for pytree inputs
    done = False

    def add_once(a):
        nonlocal done
        if done or not jnp.issubdtype(jnp.result_type(a), jnp.floating):
            return a
        done = True
        return a + z.astype(a.dtype)

    return jax.tree_util.tree_map(add_once, tree)


class Timing(float):
    """Measured seconds plus how they were measured.

    Arithmetic degrades to plain float; ``report`` lifts ``provenance``
    (harness version, dispatch mode, dispatch count) onto the JSON line
    so every persisted number is self-labeling.
    """

    def __new__(cls, seconds, provenance=None):
        self = super().__new__(cls, seconds)
        self.provenance = dict(provenance or {})
        return self


def _chain_body(fn, first, rest, warmup):
    """One chained perturbed iteration: scale the base input by an
    iteration-dependent 1% (offset past the warmup range — reusing a
    warmup scale plus _chain's exact 0.0 would hand the memoizer a
    bitwise-identical input), feed a zero derived from the previous
    output into it, run fn. Works with a Python int i (legacy host loop)
    or a traced i (in-jit fori_loop) — the SAME math either way, which
    is what tests/test_measurement.py pins.
    """

    def body(i, out):
        c = 1.0 + 0.01 * (warmup + i + 1.0)
        return fn(_chain(_scale(first, c), out), *rest)

    return body


def _warm(fn, first, rest, warmup):
    out = None
    for i in range(warmup):
        out = fn(_scale(first, 1.0 + 0.01 * (i + 1)), *rest)
    return out


def chain_result(fn, *args, iters=20, warmup=1, mode='fori_loop'):
    """Final output of the chained perturbed iteration sequence, via
    either dispatch mode — the equivalence oracle for the two timeit
    paths (no timing, just the math)."""
    first, rest = args[0], args[1:]
    out = _warm(fn, first, rest, warmup)
    body = _chain_body(fn, first, rest, warmup)
    if mode == 'fori_loop':
        looped = jax.jit(
            lambda out0: jax.lax.fori_loop(0, iters, body, out0)
        )
        return looped(out)
    for i in range(iters):
        out = body(i, out)
    return out


def timeit(fn, *args, iters=20, warmup=1, mode=None):
    """Time fn over ITERATION-CHAINED perturbed iterations, ONE dispatch
    per measurement.

    Two axon-pool hazards, both measured on the real tunnel:
    - the backend memoizes repeated identical computations (an 8-deep
      4096^3 matmul chain 'ran' in 0.04 ms — 30x above physical peak), so
      same-input loops report cache hits. A 1% iteration-dependent scale
      forces real execution (additive 1e-6 would round away in bf16).
    - INDEPENDENT dispatches overlap (or fan out across the pool), so
      block_until_ready(last) times only the final call: the perturbed
      loop still reported 8.4 PFLOP/s on one v5e chip (~20x peak).
      Feeding a zero derived from iteration i's output into iteration
      i+1's input serializes the chain without changing the math.

    The v1 harness ran that chain as iters host dispatches, so every
    number still carried one tunnel round-trip per iteration — the
    latency floor that flattened the cov sweep (ROADMAP item 2). v2
    moves the chain INSIDE jit as a ``lax.fori_loop``: the whole
    measurement is one dispatch, so per-iteration time contains at most
    1/iters of the dispatch latency. Callables that cannot trace under
    jit (AOT-compiled executables, host callbacks) fall back to the
    legacy host loop; the returned :class:`Timing` records which mode
    actually ran and how many dispatches the timed region contained.
    """
    mode = mode or _dispatch_mode()
    first, rest = args[0], args[1:]
    out0 = _warm(fn, first, rest, warmup)
    jax.block_until_ready(out0)
    body = _chain_body(fn, first, rest, warmup)
    looped = None
    if mode == 'fori_loop' and warmup >= 1:
        try:
            looped = jax.jit(
                lambda o0, f, r: jax.lax.fori_loop(
                    0, iters, _chain_body(fn, f, r, warmup), o0
                )
            )
            # untimed compile + warm run of the whole chain
            jax.block_until_ready(looped(out0, first, rest))
        except Exception:  # noqa: BLE001 - e.g. AOT executables don't trace
            looped = None
    if looped is not None:
        t0 = time.perf_counter()
        jax.block_until_ready(looped(out0, first, rest))
        seconds = (time.perf_counter() - t0) / iters
        mode, dispatches = 'fori_loop', 1
    else:
        out = out0
        t0 = time.perf_counter()
        for i in range(iters):
            out = body(i, out)
        jax.block_until_ready(out)
        seconds = (time.perf_counter() - t0) / iters
        mode, dispatches = 'legacy', iters
    return Timing(seconds, {
        'harness_version': HARNESS_VERSION,
        'dispatch_mode': mode,
        'dispatches': dispatches,
        'iters': iters,
    })


def measured(name, thunk, iters, post=None):
    """announce + time + report with per-op isolation: one unsupported op
    (round 5 on-chip: axon has no host callbacks, so eigh_host raised and
    killed the whole run) must cost one line, not the session.

    ``post``: optional callable receiving the measured seconds, returning
    extra report fields computed only on success (oracle checks, derived
    ratios). Errors report ``ms: None`` — NOT NaN, which json.dump would
    emit as a bare non-standard token that breaks strict consumers of the
    persisted bench partials."""
    announce(name)
    try:
        t = thunk(iters)
        report(name, t, **(post(t) if post else {}))
        return t
    except Exception as exc:  # noqa: BLE001
        print(json.dumps({'op': name, 'ms': None,
                          'error': f'{type(exc).__name__}: {exc}'}),
              flush=True)
        return None


def announce(name):
    """Pre-announce each measurement on stderr: when a TPU program wedges
    mid-op, the last announced line names the culprit (the round-4 bench
    died silently at an unnamed compile — never again)."""
    print(f'[micro] timing {name}', file=sys.stderr, flush=True)


def report(name, seconds, **extra):
    rec = {'op': name, 'ms': round(seconds * 1e3, 3)}
    rec.update(getattr(seconds, 'provenance', None) or {})
    rec.update(extra)
    print(json.dumps(rec), flush=True)


def report_floor_verdicts(sweeps):
    """Latency-floor check per sweep family, one ``floor/<family>`` JSON
    line each: a family whose timings stayed flat while the sweep's work
    scaled is contaminated — every number in it is the dispatch floor,
    not the op (measured: cov_dense f32 flat at 72-83 ms across
    d=256-2048 under the v1 host-loop harness). bench.py lifts these
    verdicts into the round record so contaminated numbers self-label.

    ``sweeps``: family -> (work_exponent, [(size, seconds|None), ...]).
    Returns the verdicts keyed by family.
    """
    from kfac_tpu.ops import dispatch_tables

    verdicts = {}
    for family, (exponent, points) in sorted(sweeps.items()):
        sizes = [s for s, t in points if t is not None]
        times = [t for _, t in points if t is not None]
        verdict = dispatch_tables.latency_floor_verdict(
            sizes, times, work_exponent=exponent
        )
        if verdict is not None:
            verdicts[family] = verdict
            print(json.dumps({'op': f'floor/{family}', **verdict}),
                  flush=True)
    return verdicts


def newton_schulz_inverse(a, damping, iters=25):
    """(a + damping*I)^-1 by Newton-Schulz: X_{k+1} = X_k (2I - M X_k).

    Pure matmuls (MXU-native). Converges when ||I - M X_0|| < 1; the init
    X_0 = I/trace(M) guarantees that for SPD M since trace(M) > lambda_max.
    """
    d = a.shape[-1]
    eye = jnp.eye(d, dtype=jnp.float32)
    m = a.astype(jnp.float32) + damping * eye
    x = eye / jnp.trace(m)
    for _ in range(iters):
        x = x @ (2.0 * eye - m @ x)
    return x


def bench_resnet50_inverse_update(iters: int) -> None:
    """Inverse-update wall-clock on ResNet-50's real factor shapes, exact
    dims vs size-class buckets (VERDICT r2 weak #4: dozens of per-shape
    batched decompositions, mostly padding). One device: measures compile
    + batched-op dispatch amortization, the thing classing buys."""
    import kfac_tpu
    from kfac_tpu.models import resnet
    from kfac_tpu.parallel import DistributedKFAC
    from kfac_tpu.parallel.mesh import kaisa_mesh

    m = resnet.resnet50()
    x = jnp.zeros((2, 224, 224, 3), jnp.float32)
    reg = kfac_tpu.register_model(m, x)
    mesh = kaisa_mesh(1.0, devices=jax.devices()[:1])
    for granularity in (1, 128, 256):
        cfg = kfac_tpu.KFACPreconditioner(
            registry=reg, damping=0.003, compute_method='inverse',
            inverse_solver='newton_schulz',
            bucket_granularity=granularity,
        )
        dk = DistributedKFAC(config=cfg, mesh=mesh)
        state = dk.init()
        f = jax.jit(dk.update_inverses)
        tc0 = time.perf_counter()
        jax.block_until_ready(f(state).a_inv if not dk._eigen else None)
        compile_s = time.perf_counter() - tc0
        t0 = time.perf_counter()
        reps = max(2, iters // 4)
        out = state
        for i in range(reps):
            # input-varying factors: axon memoizes repeated identical
            # computations (see timeit)
            out = f(
                out._replace(
                    a={
                        k: v * (1.0 + 0.01 * (i + 1))
                        for k, v in out.a.items()
                    }
                )
            )
        jax.block_until_ready(out.a_inv)
        report(
            f'resnet50_inv_update_gran{granularity}',
            (time.perf_counter() - t0) / reps,
            n_buckets=len(dk.buckets),
            compile_s=round(compile_s, 2),
        )


def bench_pipeline(iters: int) -> None:
    """Pipelined-LM throughput vs the dense LM (VERDICT r2 weak #5: the
    1F1B backward-slot recompute trade was a comment, not a number).

    Single-device (pipe=1): isolates pure schedule overhead — scan
    machinery, masking, and 1F1B's ~2-forwards-per-microbatch recompute —
    with zero bubble, so `tokens_per_s / dense tokens_per_s` IS the
    schedule cost. Bubble cost on real stages is (2S-2)/(M+2S-2) on top.
    """
    import kfac_tpu
    from kfac_tpu.models import TransformerLM, lm_loss
    from kfac_tpu.parallel import PipelinedLM
    from kfac_tpu.parallel.mesh import pipeline_mesh

    on_tpu = jax.devices()[0].platform == 'tpu'
    b, s, d, layers, vocab = (16, 512, 512, 4, 8192) if on_tpu else (
        4, 64, 64, 2, 128
    )
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    tokens = jax.random.randint(jax.random.PRNGKey(0), (b, s), 0, vocab)
    targets = jnp.roll(tokens, -1, 1)

    dense = TransformerLM(
        vocab_size=vocab, d_model=d, num_heads=4, num_layers=layers,
        max_len=s, dtype=dtype,
    )
    dparams = dense.init(jax.random.PRNGKey(1), tokens)['params']
    dloss = lm_loss(dense)
    g = jax.jit(jax.value_and_grad(dloss))
    t_dense = timeit(
        lambda p, bt: g(p, bt)[0], dparams, (tokens, targets),
        iters=max(3, iters // 2),
    )
    report('lm_dense_loss_grad', t_dense,
           tokens_per_s=round(b * s / t_dense, 1))

    mesh = pipeline_mesh(n_stages=1, devices=jax.devices()[:1])
    for schedule in ('gpipe', '1f1b'):
        for micro in (2, 4):
            plm = PipelinedLM(
                mesh=mesh, vocab_size=vocab, d_model=d, num_heads=4,
                num_layers=layers, n_microbatches=micro, max_len=s,
                dtype=dtype, schedule=schedule,
            )
            pparams = plm.init(jax.random.PRNGKey(1))
            f = jax.jit(
                lambda p, bt, _plm=plm: _plm.loss_and_stats(p, bt)[0]
            )
            t = timeit(
                lambda p, bt, _f=f: _f(p, bt), pparams, (tokens, targets),
                iters=max(3, iters // 2),
            )
            report(
                f'lm_pipeline_{schedule}_m{micro}', t,
                tokens_per_s=round(b * s / t, 1),
                vs_dense=round(t_dense / t, 3),
            )


def bench_vocab_head(iters: int) -> None:
    """Vocab-parallel LM head: per-device cost of head matmul + fused NLL
    must scale ~1/tp when the (d, V) kernel shards V over the model axis
    (VERDICT r3 weak #4: the replicated head is a real MFU tax at V~50k).

    Reports the compiled per-device FLOPs (the SPMD program's own cost
    model — honest on any backend, including a 1-core CPU mesh where
    wall-clock parallelism is fake) plus wall-clock for reference.
    """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from kfac_tpu.ops import losses as losses_lib

    b, s, d = 8, 128, 256
    vocab = 8192
    devs = jax.devices()
    key = jax.random.PRNGKey(3)
    x = jax.random.normal(key, (b, s, d), jnp.float32)
    targets = jax.random.randint(jax.random.PRNGKey(4), (b, s), 0, vocab)
    kernel = jax.random.normal(
        jax.random.PRNGKey(5), (d, vocab), jnp.float32
    ) * 0.02

    def loss(k, x, t):
        logits = x @ k
        return jnp.mean(losses_lib.vocab_parallel_nll(logits, t))

    import math

    grad = jax.jit(jax.value_and_grad(loss))
    tp = 1
    base_flops = None
    while tp <= len(devs):
        mesh = Mesh(devs[:tp], ('model',))
        ks = jax.device_put(kernel, NamedSharding(mesh, P(None, 'model')))
        compiled = grad.lower(ks, x, targets).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float((ca or {}).get('flops', float('nan')))
        if base_flops is None:
            base_flops = flops
        # time the AOT executable directly (a fresh grad(...) dispatch
        # would re-trace and compile the same program a second time —
        # compiles dominate on this 1-core container)
        t = timeit(lambda k_, x_, t_: compiled(k_, x_, t_)[0],
                   ks, x, targets, iters=max(3, iters // 2))
        known = not math.isnan(flops) and base_flops and not math.isnan(
            base_flops
        )
        report(
            f'vocab_head_tp{tp}', t,
            flops_per_device=None if math.isnan(flops) else flops,
            vs_tp1_flops=round(flops / base_flops, 4) if known else None,
        )
        tp *= 2


def bench_bubble() -> None:
    """Interleaved-1F1B schedule bubble accounting (kfac_tpu.parallel.
    interleaved): idle chunk-slots per total, normalized to stage-time
    units so v configurations are comparable. Pure schedule math — the
    cross-v comparison holds on any hardware. Two tick models: the
    combined-scan (F,B)-pair model caps the interleaving gain (~25% at
    p=4); the SINGLE-SLOT tables (one F OR B chunk per tick — the model
    InterleavedPipelinedLM executes) realize the full 2*(p-1)/v Megatron
    reduction."""
    from kfac_tpu.parallel import interleaved

    for p, m in ((4, 16), (8, 32)):
        base = None
        for v in (1, 2, 4):
            sched = interleaved.generate(p, v, m)
            idle = sched.bubble_slots() // p  # per-rank idle chunk-slots
            stage_units = idle / v  # chunk time = stage time / v
            if base is None:
                base = stage_units
            single = interleaved.generate_single_slot(p, v, m)
            ss_units = single.bubble_slots() / p / v
            # schedule math, not a timed measurement: no ms field
            print(json.dumps({
                'op': f'pipeline_bubble_p{p}_v{v}_m{m}',
                'ticks': sched.ticks,
                'bubble_frac': round(idle / (2 * sched.ticks), 4),
                'bubble_stage_units': round(stage_units, 2),
                'vs_v1': round(stage_units / base, 3),
                'single_slot_stage_units': round(ss_units, 2),
                'single_slot_ring': single.ring,
            }), flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--sizes', type=int, nargs='*',
                   default=[256, 512, 1024, 2048, 4096])
    p.add_argument('--iters', type=int, default=20)
    p.add_argument('--rows', type=int, default=8192)
    p.add_argument('--resnet', action='store_true',
                   help='ResNet-50 inverse-update: exact vs size-class '
                   'buckets')
    p.add_argument('--pipeline', action='store_true',
                   help='pipeline schedule overhead vs the dense LM')
    p.add_argument('--head', action='store_true',
                   help='vocab-parallel head: per-device cost vs tp')
    p.add_argument('--bubble', action='store_true',
                   help='interleaved-1F1B schedule bubble fractions '
                   '(pure schedule math, no device work)')
    p.add_argument('--skip-factor-ops', action='store_true')
    p.add_argument('--dispatch', choices=['fori_loop', 'legacy'],
                   help='measurement dispatch mode: fori_loop (default; '
                   'ONE dispatch per measurement, the chain runs in-jit) '
                   'or legacy (v1 per-iteration host dispatches, kept '
                   'for A/B-ing the harness itself)')
    p.add_argument('--smoke', action='store_true',
                   help='CI-sized pass: shrink the clock-check matmul and '
                   'skip the attention A/B so the sweep runs in seconds '
                   'on a CPU host (make prof)')
    p.add_argument('--no-pallas', action='store_true',
                   help='skip the Pallas kernels (cov + flash attention): '
                   'measure only validated XLA ops — the safe first pass '
                   'on an untested chip')
    p.add_argument('--pallas-only', action='store_true',
                   help='measure ONLY the Pallas kernels vs their XLA '
                   'oracles (on-chip validation pass; run after the safe '
                   'ops have succeeded)')
    args = p.parse_args()
    if args.dispatch:
        os.environ[DISPATCH_ENV] = args.dispatch

    dev = jax.devices()[0]
    print(json.dumps({'platform': dev.platform,
                      'device_kind': getattr(dev, 'device_kind', ''),
                      'harness_version': HARNESS_VERSION,
                      'dispatch_mode': _dispatch_mode()}),
          flush=True)

    run_pallas = not args.no_pallas
    xla_ops = not args.pallas_only
    #: family -> (work exponent wrt the swept size, [(size, seconds)]);
    #: fed to the latency-floor check after the sweep
    sweeps: dict = {}

    def track(family, exponent, size, t):
        sweeps.setdefault(family, (exponent, []))[1].append((size, t))
        return t

    # --- clock validation: known-FLOPs matmul chain -----------------------
    n = 512 if args.smoke else 4096
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)

    @jax.jit
    def mm_chain(a):
        x = a
        for _ in range(8):
            x = x @ a
        return x

    announce(f'matmul{n}_bf16_chain8')
    t = timeit(mm_chain, a, iters=args.iters)
    flops = 8 * 2 * n**3
    report(f'matmul{n}_bf16_chain8', t, tflops=round(flops / t / 1e12, 1))

    # --- flash attention kernel vs einsum attention (TPU only: the
    # kernel needs real Mosaic, and the einsum path at this size is
    # minutes on CPU) ------------------------------------------------------
    from kfac_tpu.models import attention as att
    from kfac_tpu.ops import pallas_attention as pa

    on_tpu = dev.platform == 'tpu'
    if not args.smoke:
        b, s, h, hd = (4, 2048, 4, 128) if on_tpu else (1, 256, 1, 128)
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(9), 3)
        qkv = tuple(
            jax.random.normal(kx, (b, s, h, hd), jnp.bfloat16)
            for kx in (kq, kk, kv)
        )
        dense_att = jax.jit(
            lambda q, k, v: att._finish(
                pa.attend_partials_einsum(q, k, v, 0, 0, True)
            )
        )
        announce(f'attn_einsum_s{s}')
        t = timeit(dense_att, *qkv, iters=args.iters)
        report(f'attn_einsum_s{s}', t)
        if on_tpu and run_pallas:
            flash = jax.jit(
                lambda q, k, v: att._finish(
                    pa.flash_attention_partials(q, k, v, causal=True)
                )
            )

            def flash_check(t2, _t_einsum=t):
                err = float(jnp.abs(
                    flash(*qkv).astype(jnp.float32)
                    - dense_att(*qkv).astype(jnp.float32)
                ).max())
                return {'max_err': round(err, 5),
                        'speedup': round(_t_einsum / t2, 2)}

            measured(f'attn_flash_s{s}',
                     lambda n: timeit(flash, *qkv, iters=n), args.iters,
                     post=flash_check)

    if not args.skip_factor_ops:
        for d in args.sizes:
            m = jax.random.normal(jax.random.PRNGKey(d), (args.rows, d),
                                  jnp.float32)
            cov = (m.T @ m) / args.rows  # SPD test matrix

            if xla_ops:
                qiters = max(3, args.iters // 4)
                f = jax.jit(lambda c: jnp.linalg.eigh(c))
                track('eigh', 3.0, d,
                      measured(f'eigh_{d}',
                               lambda n: timeit(f, cov, iters=n), qiters))

                # host-offloaded eigh (pure_callback -> LAPACK): the EIGEN
                # method's TPU escape hatch — measures the d^2 transfer +
                # host syevd against the device eigh above and
                # Newton-Schulz below. (Known-unsupported under axon_pjrt:
                # no host send/recv callbacks — reports the error line.)
                from kfac_tpu.ops import factors as factors_lib

                fh = jax.jit(
                    lambda c: factors_lib.batched_eigh(c, impl='host')
                )
                track('eigh_host', 3.0, d,
                      measured(f'eigh_host_{d}',
                               lambda n: timeit(fh, cov, iters=n), qiters))

                # cholesky factor + solve against identity (INVERSE method)
                def chol_inv(c):
                    l = jax.scipy.linalg.cho_factor(
                        c + 0.003 * jnp.eye(d, dtype=c.dtype)
                    )
                    return jax.scipy.linalg.cho_solve(
                        l, jnp.eye(d, dtype=c.dtype)
                    )

                track('cholesky_inv', 3.0, d,
                      measured(f'cholesky_inv_{d}',
                               lambda n: timeit(
                                   jax.jit(chol_inv), cov, iters=n
                               ),
                               qiters))

                # Newton-Schulz damped inverse: 2*iters MXU matmuls, the
                # library's TPU default (default_compute_method)
                ns = jax.jit(lambda c: newton_schulz_inverse(c, 0.003))

                def ns_residual(_t):
                    x = ns(cov)
                    err = float(jnp.abs(
                        x @ (cov + 0.003 * jnp.eye(d)) - jnp.eye(d)
                    ).max())
                    return {'residual_inf': round(err, 6)}

                track('newton_schulz25', 3.0, d,
                      measured(f'newton_schulz25_{d}',
                               lambda n: timeit(ns, cov, iters=n), qiters,
                               post=ns_residual))

                # warm-started refresh at factor-EMA drift (the library
                # passes the previous inverse as x0 at every
                # inv_update_steps refresh; residual-based early exit
                # means wall-clock ~ iterations actually taken)
                from kfac_tpu.ops import factors as fwarm

                drift = 0.95 * cov + 0.05 * jnp.eye(d, dtype=cov.dtype)
                prev_inv = fwarm.newton_schulz_inverse(cov, 0.003)
                warm = jax.jit(
                    lambda c: fwarm.newton_schulz_inverse(
                        c, 0.003, x0=prev_inv
                    )
                )

                def warm_iters(_t):
                    info = fwarm.newton_schulz_inverse_info(
                        drift, 0.003, x0=prev_inv
                    )
                    cold = fwarm.newton_schulz_inverse_info(drift, 0.003)
                    return {
                        'warm_iters': int(info.iterations),
                        'cold_iters': int(cold.iterations),
                    }

                track('newton_schulz_warm', 3.0, d,
                      measured(f'newton_schulz_warm_{d}',
                               lambda n: timeit(warm, drift, iters=n),
                               qiters, post=warm_iters))

            # covariance: XLA dense contraction vs Pallas triangular kernel
            for dt, tag in ((jnp.float32, 'f32'), (jnp.bfloat16, 'bf16')):
                md = m.astype(dt)
                dense = jax.jit(
                    lambda a: jax.lax.dot_general(
                        a, a, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ) / a.shape[0]
                )
                announce(f'cov_dense_{d}_{tag}')
                t = timeit(dense, md, iters=args.iters)
                report(f'cov_dense_{d}_{tag}', t)
                track(f'cov_dense_{tag}', 2.0, d, t)
                if run_pallas:
                    from kfac_tpu.ops import pallas_cov

                    def cov_check(_t, _md=md, _dense=dense):
                        got = pallas_cov.sym_cov(_md)
                        want = _dense(_md).astype(got.dtype)
                        err = float(jnp.abs(
                            got.astype(jnp.float32)
                            - want.astype(jnp.float32)
                        ).max())
                        return {'max_err': round(err, 5)}

                    track(f'cov_pallas_{tag}', 2.0, d, measured(
                        f'cov_pallas_{d}_{tag}',
                        lambda n, _md=md: timeit(
                            jax.jit(lambda a: pallas_cov.sym_cov(a)), _md,
                            iters=n,
                        ),
                        args.iters, post=cov_check,
                    ))

            # fused step-path kernels vs their unfused XLA expressions
            # (interpret mode off-TPU: numerics-true, and the derivation
            # can only HOLD priors on a losing or contaminated sweep —
            # committed CPU evidence never opens a fused gate)
            if run_pallas:
                from kfac_tpu.ops import pallas_cov_ema, pallas_ns

                interp = pallas_ns.interpret_mode()
                beta = 0.95
                coeff = (1.0 - beta) / args.rows
                f0 = cov + jnp.eye(d, dtype=jnp.float32)

                def ema_unfused(f, a, _beta=beta, _coeff=coeff):
                    acc = jax.lax.dot_general(
                        a, a, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    return _beta * f + _coeff * acc

                track('cov_ema_unfused', 2.0, d, measured(
                    f'cov_ema_unfused_{d}_f32',
                    lambda n: timeit(jax.jit(ema_unfused), f0, m, iters=n),
                    args.iters,
                ))
                track('cov_ema_fused', 2.0, d, measured(
                    f'cov_ema_fused_{d}_f32',
                    lambda n: timeit(
                        jax.jit(lambda f, a: pallas_cov_ema._fused(
                            f, a, beta, coeff, interpret=interp
                        )),
                        f0, m, iters=n,
                    ),
                    args.iters,
                ))

                damping = 0.003
                m_spd = cov + damping * jnp.eye(d, dtype=jnp.float32)
                x0 = jnp.eye(d, dtype=jnp.float32) / jnp.trace(m_spd)
                mx0 = m_spd @ x0

                def ns_unfused(mm, x, mx):
                    eye = jnp.eye(mm.shape[-1], dtype=jnp.float32)
                    x_new = x @ (2.0 * eye - mx)
                    mx_new = mm @ x_new
                    r = jnp.linalg.norm(eye - mx_new) / jnp.sqrt(
                        jnp.float32(mm.shape[-1])
                    )
                    return x_new, mx_new, r

                track('ns_unfused', 3.0, d, measured(
                    f'ns_unfused_{d}',
                    lambda n: timeit(jax.jit(ns_unfused), m_spd, x0, mx0,
                                     iters=n),
                    args.iters,
                ))
                if d % pallas_ns.TILE == 0:
                    track('ns_fused', 3.0, d, measured(
                        f'ns_fused_{d}',
                        lambda n: timeit(
                            jax.jit(
                                lambda mm, x, mx: pallas_ns.fused_ns_step(
                                    mm, x, mx, interpret=interp
                                )
                            ),
                            m_spd, x0, mx0, iters=n,
                        ),
                        args.iters,
                    ))

                gmat = 0.5 * cov + 0.1 * jnp.eye(d, dtype=jnp.float32)

                def kl_unfused(p, g):
                    return p * jnp.sum(p * g)

                def kl_fused(p, g):
                    s = pallas_ns.fused_klclip_dot(p, g, interpret=interp)
                    return pallas_ns.fused_klclip_scale(
                        p, s, interpret=interp
                    )

                track('klclip_unfused', 2.0, d, measured(
                    f'klclip_unfused_{d}',
                    lambda n: timeit(jax.jit(kl_unfused), cov, gmat,
                                     iters=n),
                    args.iters,
                ))
                track('klclip_fused', 2.0, d, measured(
                    f'klclip_fused_{d}',
                    lambda n: timeit(jax.jit(kl_fused), cov, gmat,
                                     iters=n),
                    args.iters,
                ))

    if sweeps:
        report_floor_verdicts(sweeps)

    if args.resnet:
        bench_resnet50_inverse_update(args.iters)
    if args.pipeline:
        bench_pipeline(args.iters)
    if args.head:
        bench_vocab_head(args.iters)
    if args.bubble:
        bench_bubble()


if __name__ == '__main__':
    main()
