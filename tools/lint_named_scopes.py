#!/usr/bin/env python
"""Lint: every public jitted engine entry point carries a named scope.

Thin wrapper kept for ``make obs`` and existing imports; the check now
lives in the kfaclint registry as rule **KFL101** (see
``kfac_tpu/analysis/drift.py`` and docs/ANALYSIS.md). Prefer:

    JAX_PLATFORMS=cpu python tools/kfaclint.py --rules KFL101
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: E402

_common.bootstrap()

from kfac_tpu.analysis import drift  # noqa: E402

TARGETS = drift.SCOPE_TARGETS


def check() -> list[str]:
    """Return a list of 'module.Class.method' strings missing a scope."""
    return drift.check_named_scopes()


def main() -> int:
    missing = check()
    if missing:
        print('missing named scopes (tracing.trace/tracing.scope):')
        for m in missing:
            print(f'  {m}')
        return 1
    n = sum(len(m) for _, _, m in TARGETS)
    print(f'named-scope lint ok: {n} entry points annotated')
    return 0


if __name__ == '__main__':
    sys.exit(main())
