#!/usr/bin/env python
"""Lint: every public jitted engine entry point carries a named scope.

The observability spine (docs/OBSERVABILITY.md) relies on the engines'
hot paths being wrapped in ``jax.named_scope`` — that is what makes XLA
profiler captures attribute device time to K-FAC phases. Both
``kfac_tpu.tracing.trace`` and ``kfac_tpu.tracing.scope`` stamp a
``__kfac_scope__`` attribute on the functions they wrap; this script
asserts the attribute is present on every entry point below so a
refactor cannot silently drop the annotation.

Run via ``make obs`` (CPU-pinned) or directly:

    JAX_PLATFORMS=cpu python tools/lint_named_scopes.py
"""

from __future__ import annotations

import importlib
import inspect
import os
import sys

# (module, class-or-None, callables that must carry __kfac_scope__);
# a None class means module-level functions
TARGETS: list[tuple[str, str | None, tuple[str, ...]]] = [
    (
        'kfac_tpu.preconditioner',
        'KFACPreconditioner',
        ('step', 'update_factors', 'update_inverses', 'precondition'),
    ),
    (
        'kfac_tpu.parallel.kaisa',
        'DistributedKFAC',
        ('step', 'update_factors', 'update_inverses', 'precondition'),
    ),
    (
        'kfac_tpu.training',
        'Trainer',
        ('step', 'scan_steps', 'step_accumulate', 'step_accumulate_scan'),
    ),
    (
        'kfac_tpu.async_inverse.sliced',
        None,
        ('dense_async_step', 'kaisa_async_step'),
    ),
    (
        'kfac_tpu.async_inverse.host',
        None,
        ('dense_host_step', 'kaisa_host_step', 'pump'),
    ),
]


def check() -> list[str]:
    """Return a list of 'module.Class.method' strings missing a scope."""
    missing: list[str] = []
    for mod_name, cls_name, methods in TARGETS:
        mod = importlib.import_module(mod_name)
        holder = mod if cls_name is None else getattr(mod, cls_name)
        for meth in methods:
            # getattr_static avoids triggering descriptors/binding; the
            # decorators stamp the underlying function object.
            fn = inspect.getattr_static(holder, meth)
            fn = getattr(fn, '__func__', fn)
            if not getattr(fn, '__kfac_scope__', None):
                where = mod_name if cls_name is None else f'{mod_name}.{cls_name}'
                missing.append(f'{where}.{meth}')
    return missing


def main() -> int:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    # the repo is not pip-installed; make `python tools/...` work from root
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    missing = check()
    if missing:
        print('missing named scopes (tracing.trace/tracing.scope):')
        for m in missing:
            print(f'  {m}')
        return 1
    n = sum(len(m) for _, _, m in TARGETS)
    print(f'named-scope lint ok: {n} entry points annotated')
    return 0


if __name__ == '__main__':
    sys.exit(main())
