#!/usr/bin/env python
"""Unified run-ledger CLI: cross-stream timelines and the bench sentinel.

Offline triage and CI gating over the ledger layer
(``kfac_tpu/observability/ledger.py``, see docs/OBSERVABILITY.md "Run
ledger"):

    # correlated anomaly timeline over a run directory of stream files
    python tools/kfac_ledger.py --timeline runs/2026-08-06/

    # rebuild the committed perf baseline from committed bench rounds
    python tools/kfac_ledger.py --build-baseline BENCH_r0*.json \\
        --out bench_runs/LEDGER.json

    # gate one round against the baseline (CI: nonzero exit on
    # regression); exit 0 ok, 1 regressed, 2 provenance refused
    python tools/kfac_ledger.py --check bench_runs/run_X.json \\
        --baseline bench_runs/LEDGER.json

Deliberately runnable on machines without jax: the ledger module is
loaded standalone from its file, never through the package ``__init__``.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Any

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_ledger() -> Any:
    """Load the stdlib-only ledger module without importing kfac_tpu
    (whose ``__init__`` imports jax)."""
    path = os.path.join(
        _REPO_ROOT, 'kfac_tpu', 'observability', 'ledger.py')
    spec = importlib.util.spec_from_file_location('_kfac_ledger', path)
    assert spec is not None and spec.loader is not None
    module = importlib.util.module_from_spec(spec)
    # dataclasses resolves the defining module through sys.modules
    sys.modules['_kfac_ledger'] = module
    spec.loader.exec_module(module)
    return module


def _load_round(path: str) -> dict[str, Any]:
    with open(path, encoding='utf-8') as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f'{path}: bench round must be a JSON object')
    return data


def _timeline(ledger: Any, path: str, as_json: bool) -> int:
    led = ledger.RunLedger()
    if os.path.isdir(path):
        counts = led.ingest_dir(path)
        if not counts:
            print(f'error: no recognizable stream files under {path}',
                  file=sys.stderr)
            return 2
    else:
        # a single mixed JSONL: compile heartbeats + metric records
        records = []
        with open(path, encoding='utf-8') as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
        compile_recs = [r for r in records
                        if r.get('kind') == 'compile' and 'phase' in r]
        metric_recs = [r for r in records if r not in compile_recs]
        if compile_recs:
            led.ingest('compile', compile_recs)
        if metric_recs:
            led.ingest('metrics', metric_recs)
        led.assign_steps()
    if as_json:
        json.dump(ledger.timeline_report(led), sys.stdout, indent=2,
                  sort_keys=True)
        print()
    else:
        sys.stdout.write(ledger.render_timeline(led))
    return 0


def _check(ledger: Any, round_path: str, baseline_path: str,
           as_json: bool) -> int:
    round_json = _load_round(round_path)
    baseline = None
    if os.path.exists(baseline_path):
        baseline = ledger.load_baseline(baseline_path)
    verdict = ledger.sentinel_check(round_json, baseline)
    if as_json:
        json.dump(verdict, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        status = verdict['status']
        print(f'ledger sentinel: {status}'
              + (f" ({verdict['reason']})" if status == 'refused' else ''))
        for key, entry in sorted(verdict['keys'].items()):
            ratio = entry.get('ratio')
            print(f"  {key:<22} {entry['verdict']:<10}"
                  f" measured={entry['measured']}"
                  f" baseline={entry['baseline']:g}"
                  f" tol={entry['tolerance']:g} ({entry['direction']})"
                  + (f' ratio={ratio:.3f}' if ratio is not None else ''))
        if verdict['regressed_keys']:
            print('  REGRESSED: ' + ', '.join(verdict['regressed_keys']))
    if verdict['status'] == 'regressed':
        return 1
    if verdict['status'] == 'refused':
        return 2
    return 0


def _build_baseline(ledger: Any, round_paths: list[str], out: str,
                    window: int | None) -> int:
    rounds = [_load_round(p) for p in round_paths]
    config = ledger.LedgerConfig(sentinel_window=window) if window \
        else ledger.LedgerConfig()
    baseline = ledger.build_baseline(
        rounds, config=config,
        sources=[os.path.basename(p) for p in round_paths])
    ledger.save_baseline(out, baseline)
    print(f"wrote {out}: platform={baseline['platform']}"
          f" rounds={baseline['n_rounds']}"
          f" (dropped {baseline['n_dropped_provenance']} off-provenance)"
          f" keys={','.join(sorted(baseline['keys']))}")
    return 0


def selftest() -> int:
    """Processless checks of the full ledger surface: adapters,
    correlation, sentinel verdicts, baseline determinism."""
    import tempfile
    ledger = _load_ledger()

    # header vs header-less run identification
    events = ledger.parse_metrics([
        ledger.run_header('abc123', 'metrics'),
        {'step': 0, 'loss': 1.0}])
    assert events[0]['run_id'] == 'abc123', events
    bare = ledger.parse_metrics([{'step': 0, 'loss': 1.0}])
    assert bare[0]['run_id'] is None, bare

    # correlated timeline over synthesized streams joins >= 3 streams
    led = ledger.RunLedger()
    led.ingest('chaos', [{'event': 'step', 'step': s, 't': 500.0 + s}
                         for s in (0, 4, 8)])
    led.ingest('compile', [
        {'kind': 'compile', 'phase': 'lowering', 'entry': 'kfac.step',
         'n': 2, 'pid': 7, 't': 503.1},
        {'kind': 'compile', 'phase': 'done', 'entry': 'kfac.step',
         'n': 2, 'pid': 7, 't': 503.9}])
    led.ingest('metrics', [
        {'step': s, 'step_time_s': 0.5 if s == 4 else 0.1}
        for s in range(8)])
    led.ingest('calibration', [{'step': 5, 'calib/model_error': 2.0}])
    led.ingest('fleet', [{'event': 'armed', 'step': 6, 'detail': ''}])
    led.assign_steps()
    annotations = led.correlations()
    cascade = [a for a in annotations if a['rule'] == 'recompile_cascade']
    assert cascade and len(cascade[0]['streams']) >= 3, annotations
    text = ledger.render_timeline(led)
    assert 'recompile_cascade' in text and 'step_time_spike' in text, text
    assert ledger.render_timeline(led) == text  # deterministic

    # clean negative: no recompile -> no cascade
    led2 = ledger.RunLedger()
    led2.ingest('metrics', [
        {'step': s, 'step_time_s': 0.5 if s == 4 else 0.1}
        for s in range(8)])
    led2.ingest('fleet', [{'event': 'armed', 'step': 6, 'detail': ''}])
    assert not [a for a in led2.correlations()
                if a['rule'].startswith('recompile')], led2.correlations()

    # died-compiling + divergence verdicts surface in ONE report
    led3 = ledger.RunLedger()
    led3.ingest('compile', [
        {'kind': 'compile', 'phase': 'lowering', 'entry': 'trainer.step',
         'n': 1, 'pid': 9, 't': 1.0}])
    led3.ingest('metrics', [{'step': 3, 'loss': float('nan')}])
    report = ledger.timeline_report(led3)
    assert 'died compiling trainer.step' in report['verdicts']['compile']
    assert 'nonfinite_loss' in report['verdicts']['divergence']

    # sentinel: pass / 1.5x regression / provenance refusal
    rounds = [{'parsed': {'platform': 'cpu', 'device_kind': 'cpu',
                          'value': 100.0 + n, 'sgd_tokens_per_sec': 140.0}}
              for n in range(5)]
    base = ledger.build_baseline(rounds, sources=['r%d' % n
                                                  for n in range(5)])
    ok = ledger.sentinel_check(
        {'parsed': {'platform': 'cpu', 'value': 101.0,
                    'sgd_tokens_per_sec': 139.0}}, base)
    assert ok['status'] == 'ok', ok
    bad = ledger.sentinel_check(
        {'parsed': {'platform': 'cpu', 'value': 102.0 / 1.5,
                    'sgd_tokens_per_sec': 139.0}}, base)
    assert bad['status'] == 'regressed', bad
    assert bad['regressed_keys'] == ['value'], bad
    refused = ledger.sentinel_check(
        {'parsed': {'platform': 'tpu', 'value': 1e6}}, base)
    assert refused['status'] == 'refused' and not refused['keys'], refused
    none = ledger.sentinel_check({'parsed': {'platform': 'cpu'}}, None)
    assert none['status'] == 'no_baseline', none

    # baseline artifact: atomic, deterministic, schema-checked
    with tempfile.TemporaryDirectory() as tmp:
        p1 = os.path.join(tmp, 'a.json')
        p2 = os.path.join(tmp, 'b.json')
        ledger.save_baseline(p1, base)
        ledger.save_baseline(p2, base)
        b1 = open(p1, 'rb').read()
        assert b1 == open(p2, 'rb').read()
        assert ledger.load_baseline(p1) == base
        with open(p1, 'w') as f:
            json.dump({'kind': 'something_else'}, f)
        try:
            ledger.load_baseline(p1)
            raise AssertionError('expected ValueError')
        except ValueError:
            pass
    print('kfac_ledger selftest: ok')
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    parser.add_argument('--timeline', metavar='PATH',
                        help='run directory (or mixed JSONL) to render '
                             'as a correlated anomaly timeline')
    parser.add_argument('--build-baseline', nargs='+', metavar='ROUND',
                        help='bench round JSONs to fold into a baseline')
    parser.add_argument('--out', default='bench_runs/LEDGER.json',
                        help='baseline output path for --build-baseline')
    parser.add_argument('--window', type=int, default=None,
                        help='override the sentinel median window')
    parser.add_argument('--check', metavar='ROUND',
                        help='bench round JSON to gate against --baseline')
    parser.add_argument('--baseline', default='bench_runs/LEDGER.json',
                        help='baseline artifact for --check')
    parser.add_argument('--json', action='store_true',
                        help='emit machine-readable JSON instead of text')
    parser.add_argument('--selftest', action='store_true',
                        help='run the built-in checks and exit')
    args = parser.parse_args(argv)

    if args.selftest:
        return selftest()
    ledger = _load_ledger()
    if args.timeline:
        return _timeline(ledger, args.timeline, args.json)
    if args.build_baseline:
        return _build_baseline(
            ledger, args.build_baseline, args.out, args.window)
    if args.check:
        return _check(ledger, args.check, args.baseline, args.json)
    parser.error(
        'one of --timeline / --build-baseline / --check / --selftest '
        'is required')
    return 2


if __name__ == '__main__':
    sys.exit(main())
