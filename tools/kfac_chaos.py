#!/usr/bin/env python
"""Chaos-harness CLI: run a preemption storm, commit its SLO artifact.

Drives :class:`kfac_tpu.resilience.chaos.ChaosConductor` — a real
multi-process gloo pod under scripted or seeded preemption storms —
and writes the reconciled :class:`ChaosReport` JSON. The committed
artifact (``kfac_tpu/resilience/chaos_slo.json``) is what ``bench.py``'s
``_chaos_probe`` and the docs/ROBUSTNESS.md SLO table fold in.

Usage:

    python tools/kfac_chaos.py --selftest
        No-process sanity pass: schedule grammar, reconcile math, and
        budget detection on synthetic pod records (seconds, runs in CI).

    python tools/kfac_chaos.py [--procs 4] [--max-steps 12] [--seed N]
        Run the storm (canonical scripted storm unless --seed) in a
        temp root and print the SLO rows. Add
        ``--out kfac_tpu/resilience/chaos_slo.json`` to (re)commit the
        artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: E402

_common.bootstrap()


def selftest() -> int:
    """Processless checks of the conductor's pure machinery."""
    from kfac_tpu.resilience import chaos

    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        (failures.append(what) if not cond else None)
        print(f'  {"ok " if cond else "FAIL"} {what}')

    cfg = chaos.ChaosConfig()
    sched = chaos.resolve_schedule(cfg)
    check(
        {e['fault'] for e in sched} >= {
            'sigterm_wave', 'torn_checkpoint', 'shrink', 'sigusr1'},
        'canonical scripted storm covers the committed fault classes',
    )
    check(
        all(e['fault'] in chaos.FAULT_CLASSES for e in sched),
        'scripted storm uses only declared fault classes',
    )
    seeded = chaos.seeded_storm(chaos.ChaosConfig(seed=7))
    check(
        seeded == chaos.seeded_storm(chaos.ChaosConfig(seed=7)),
        'seeded storm is deterministic per seed',
    )
    check(
        seeded != chaos.seeded_storm(chaos.ChaosConfig(seed=8)),
        'different seeds draw different storms',
    )

    # reconcile math on synthetic pod records: a clean respawn and a
    # blown-budget respawn must classify correctly without any process
    def rec(procs, down, events):
        r = chaos.RunRecord(procs=procs, skew=0.0, down_event=down)
        r.events = events
        r.t_exit = 10.0
        return r

    def step_ev(rank, t, step, loss):
        return (rank, t, {'event': 'step', 'step': step, 'loss': loss})

    def start_ev(rank, t, resumed, depth):
        return (rank, t, {
            'event': 'start', 'rank': rank, 'world': 2,
            'resumed_step': resumed, 'fallback_depth': depth,
        })

    down = {'fault': 'sigterm_wave', 'ranks': (0,), 'at_step': 2}
    losses = {1: 1.0, 2: 0.5, 3: 0.25, 4: 0.125}
    runs = [{'down': down, 'snaps': ()}, {'down': None, 'snaps': ()}]
    records = [
        rec(2, down, [start_ev(r, 1.0, 0, 0) for r in (0, 1)]
            + [step_ev(r, 2.0, s, losses[s])
               for r in (0, 1) for s in (1, 2)]),
        rec(2, None, [start_ev(r, 11.0, 2, 0) for r in (0, 1)]
            + [step_ev(r, 12.0, s, losses[s])
               for r in (0, 1) for s in (3, 4)]),
    ]
    control = rec(2, None, [
        step_ev(r, 1.0, s, losses[s]) for r in (0, 1) for s in losses
    ])
    cfg4 = chaos.ChaosConfig(procs=2, max_steps=4)
    report = chaos.reconcile(cfg4, runs, records, control)
    check(report.ok, 'clean synthetic storm reconciles with no blown budget')
    check(
        report.rows['sigterm_wave']['downtime_steps'] == 0,
        'boundary-step resume counts zero downtime',
    )

    diverged = [
        records[0],
        rec(2, None, [start_ev(r, 11.0, 2, 0) for r in (0, 1)]
            + [step_ev(r, 12.0, s, losses[s] + 0.5)
               for r in (0, 1) for s in (3, 4)]),
    ]
    report2 = chaos.reconcile(cfg4, runs, diverged, control)
    check(
        any('diverged' in b for b in report2.blown),
        'trajectory divergence vs control is detected',
    )
    deep = [
        records[0],
        rec(2, None, [start_ev(r, 11.0, 0, 3) for r in (0, 1)]
            + [step_ev(r, 12.0, s, losses[s])
               for r in (0, 1) for s in (1, 2, 3, 4)]),
    ]
    report3 = chaos.reconcile(cfg4, runs, deep, control)
    check(
        any('fell back' in b for b in report3.blown),
        'over-budget fallback depth is detected',
    )

    if failures:
        print(f'chaos selftest: {len(failures)} FAILED')
        return 1
    print('chaos selftest ok')
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--selftest', action='store_true',
                    help='processless sanity checks, no pods spawned')
    ap.add_argument('--procs', type=int, default=4)
    ap.add_argument('--max-steps', type=int, default=12)
    ap.add_argument('--seed', type=int, default=None,
                    help='seeded random storm instead of the canonical '
                         'scripted one')
    ap.add_argument('--storm-events', type=int, default=3)
    ap.add_argument('--use-fleet', action='store_true')
    ap.add_argument('--root', default=None,
                    help='conductor scratch dir (default: a tempdir)')
    ap.add_argument('--out', default=None,
                    help='write the full report JSON here (e.g. the '
                         'committed kfac_tpu/resilience/chaos_slo.json)')
    args = ap.parse_args()

    if args.selftest:
        return selftest()

    from kfac_tpu.resilience import chaos

    config = chaos.ChaosConfig(
        procs=args.procs,
        max_steps=args.max_steps,
        seed=args.seed,
        storm_events=args.storm_events,
        use_fleet=args.use_fleet,
    )
    root = args.root or tempfile.mkdtemp(prefix='kfac_chaos_')
    print(f'chaos storm: procs={config.procs} max_steps={config.max_steps} '
          f'{"seed=" + str(config.seed) if config.seed is not None else "scripted"} '
          f'root={root}')
    conductor = chaos.ChaosConductor(config, root=root)
    try:
        report = conductor.run()
    except chaos.ChaosError as err:
        report = getattr(err, 'report', None)
        print(f'CHAOS FAILED: {err}')
        if report is not None and args.out:
            with open(args.out, 'w') as f:
                json.dump(report.to_json(), f, indent=1, sort_keys=True)
        return 1
    print(json.dumps(report.rows, indent=1, sort_keys=True))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report.to_json(), f, indent=1, sort_keys=True)
            f.write('\n')
        print(f'wrote {args.out}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
