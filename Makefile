# CI entry points — the counterpart of the reference's tox.ini
# (/root/reference/tox.ini:1-21) for a non-pip-installed JAX library.
#
# Two tiers (pyproject.toml markers):
#   test-fast  pre-commit tier: `-m 'not slow'`
#   test       full suite — measured 7:45 warm-cache on a 1-core host,
#              inside the reference's 15-minute CI budget
#              (.github/workflows/tests.yml:12)
#
# All targets pin the host platform (the 8-virtual-device CPU mesh the
# suite is written against) and scrub the axon TPU plugin registration,
# which would otherwise hang the first jax.devices() on tunnel-equipped
# hosts.

PY ?= python
TEST_ENV = JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
	XLA_FLAGS="--xla_force_host_platform_device_count=8"

.PHONY: test test-fast test-unit test-integration faults async compress fleet chaos compilewatch ledger serve obs prof tune resilience lint lint-ir lint-pod inspect bench bench-acc native

test:
	$(TEST_ENV) $(PY) -m pytest tests/ -q

test-fast:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -m 'not slow'

# unit/integration partition the suite for CI (the reference's
# tests.yml + integration.yml split); `test` is the run-everything entry
test-unit:
	$(TEST_ENV) $(PY) -m pytest tests/ -q --ignore=tests/integration

test-integration:
	$(TEST_ENV) $(PY) -m pytest tests/integration/ -q

# numerical-health sentinel fault-injection suite (includes its slow
# distributed cases; see docs/ROBUSTNESS.md)
faults:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -m faults

# async curvature refresh: double-buffered inverse suite (sliced +
# host backends, staleness/quarantine/checkpoint semantics); the
# named-scope lint covers the async entry points too
async:
	$(TEST_ENV) $(PY) -m pytest tests/test_async_inverse.py -q
	$(TEST_ENV) $(PY) tools/lint_named_scopes.py

# compressed curvature collectives + cold-factor host offload:
# quantization/error-feedback/offload suite (bit-exactness, wire-ratio
# and convergence-parity gates; see docs/ARCHITECTURE.md
# "Compression & offload")
compress:
	$(TEST_ENV) $(PY) -m pytest tests/test_compression.py -q

# self-driving fleet: retune-on-restore + drift-triggered live layout
# migration suite (see docs/ROBUSTNESS.md "Self-driving fleet")
fleet:
	$(TEST_ENV) $(PY) -m pytest tests/test_fleet.py -q

# pod-scale chaos harness: CLI selftest (processless reconcile/grammar
# checks) + the chaos suite including the deterministic 4-proc scripted
# storm against a real gloo pod; the 16-proc seeded storm rides behind
# the `slow` marker (see docs/ROBUSTNESS.md "Chaos harness")
chaos:
	$(TEST_ENV) $(PY) tools/kfac_chaos.py --selftest
	$(TEST_ENV) $(PY) -m pytest tests/test_chaos.py -q -m 'not slow'

# measurement-truth layer (docs/OBSERVABILITY.md "Measurement truth"):
# a real microbench smoke sweep on the CPU backend (fori_loop one-
# dispatch provenance + latency-floor verdicts over an actual size
# sweep), the threshold-derivation selftest, a derivation run over the
# smoke sweep's output, and the measurement + calibration test suites
prof:
	$(TEST_ENV) $(PY) tools/tpu_microbench.py --smoke --no-pallas \
		--sizes 128 256 --iters 2 --rows 512 > /tmp/kfac_prof_micro.jsonl
	$(TEST_ENV) $(PY) tools/derive_dispatch_tables.py --selftest
	$(TEST_ENV) $(PY) tools/derive_dispatch_tables.py \
		/tmp/kfac_prof_micro.jsonl --out /tmp/kfac_prof_tables.json
	$(TEST_ENV) $(PY) -m pytest tests/test_measurement.py \
		tests/test_calibration.py -q

# compile & memory truth (docs/OBSERVABILITY.md "Compile & memory
# truth"): recompile attribution / XLA memory accounting / mid-compile
# heartbeat suite on both engines, plus the kfac_inspect selftest that
# covers the "died compiling X" journal verdict
compilewatch:
	$(TEST_ENV) $(PY) -m pytest tests/test_compile_watch.py -q -m 'not slow'
	$(PY) tools/kfac_inspect.py --selftest

# unified run ledger: adapter/correlation/sentinel suite, the
# kfac_ledger CLI selftest, and the committed-fixture timeline +
# sentinel runs (byte-stable golden, provenance-matched check)
ledger:
	$(TEST_ENV) $(PY) -m pytest tests/test_ledger.py -q -m 'not slow'
	$(PY) tools/kfac_ledger.py --selftest
	$(PY) tools/kfac_ledger.py --timeline tests/data/mini_ledger >/dev/null
	$(PY) tools/kfac_ledger.py --check tests/data/mini_ledger/bench_round.json \
		--baseline tests/data/mini_ledger/LEDGER.json

# posterior serving tier: bucketed-engine suite (MC/closed-form parity
# across padding buckets, routing, zero-recompile pins, KFL114) and the
# kfac_serve CLI selftest (see docs/SERVING.md)
serve:
	$(TEST_ENV) $(PY) -m pytest tests/test_serving.py -q
	$(TEST_ENV) $(PY) tools/kfac_serve.py --selftest

# telemetry spine: observability + flight-recorder test suites, the
# compression/offload suite (its wire-bytes accounting is part of the
# comms report contract), the self-driving fleet suite (its drift
# detector consumes the flight recorder's skew columns), the
# measurement-truth layer (prof: dispatch-free microbench, threshold
# derivation, calibration), the compile & memory truth layer
# (compilewatch: recompile attribution, XLA memory accounting,
# mid-compile heartbeats), the unified static-analysis pass (which
# includes the named-scope, metric-key, plan-schema, compression-knob,
# fleet-knob, calibration-knob, topology-knob, chaos-knob and
# compile-watch-knob lints as
# KFL101-KFL103/KFL105/KFL106/KFL108/KFL109/KFL111/KFL112 plus the
# IR-tier smoke pass via lint-ir), the unified run ledger (ledger:
# adapters, correlation timeline, perf-regression sentinel, KFL113),
# the posterior serving tier (serve: bucketed-engine parity + routing +
# recompile pins + the kfac_serve selftest, KFL114), and the
# kfac_inspect analysis selftest (see docs/OBSERVABILITY.md)
obs: async lint compress fleet chaos prof compilewatch ledger serve
	$(TEST_ENV) $(PY) -m pytest tests/test_observability.py \
		tests/test_flight_recorder.py -q
	$(PY) tools/kfac_inspect.py --selftest

# kfaclint IR tier alone (KFL201-KFL205), smoke profile: traces only
# the dense-transport d=64 eigen config so wall-clock stays bounded;
# the full strategy x method x transport matrix runs behind the `slow`
# marker in tests/test_kfaclint_ir.py (see docs/ANALYSIS.md "IR tier")
lint-ir:
	$(TEST_ENV) $(PY) tools/kfaclint.py --ir --smoke

# kfaclint pod tier alone (KFL301-KFL305): cross-rank SPMD protocol
# verification — rank-forking abstract interpretation plus the
# protocol-table model check (see docs/ANALYSIS.md "Pod tier")
lint-pod:
	$(TEST_ENV) $(PY) tools/kfaclint.py --pod

# kfaclint: AST rules (KFL001-KFL005) + docs-vs-code drift rules
# (KFL100-KFL109) + IR rules (KFL201-KFL205, smoke profile) + pod rules
# (KFL301-KFL305) + the analyzer's own fixture selftest and test suites
# (see docs/ANALYSIS.md). The --all pass runs under `timeout` as a
# wall-clock budget assertion: every tier together must stay a
# pre-commit-sized check, not a test suite
lint: lint-ir lint-pod
	$(TEST_ENV) timeout -k 10 300 $(PY) tools/kfaclint.py --all --smoke
	$(TEST_ENV) $(PY) tools/kfaclint.py --selftest
	$(TEST_ENV) $(PY) -m pytest tests/test_kfaclint.py \
		tests/test_kfaclint_ir.py tests/test_kfaclint_pod.py \
		-q -m 'not slow'

# layout autotuner: test suite, the plan-schema doc lint, and the
# end-to-end kfac_tune pipeline selftest (see docs/AUTOTUNE.md)
tune:
	$(TEST_ENV) $(PY) -m pytest tests/test_autotune.py -q
	$(TEST_ENV) $(PY) tools/lint_plan_schema.py
	$(TEST_ENV) $(PY) tools/kfac_tune.py --selftest

# preemption-safe training: checkpoint-autopilot suite (includes the
# slow real-kill subprocess test) and the signal-semantics doc lint
# (see docs/ROBUSTNESS.md "Preemption & resume")
resilience:
	$(TEST_ENV) $(PY) -m pytest tests/test_resilience.py -q
	$(TEST_ENV) $(PY) tools/lint_signals.py

# offline triage: divergence timeline from a metrics JSONL or a
# flight-recorder postmortem bundle directory
#   make inspect BUNDLE=postmortems/postmortem-step00000042-skip
inspect:
	$(PY) tools/kfac_inspect.py $(BUNDLE)

bench:
	$(PY) bench.py

bench-acc:
	$(TEST_ENV) $(PY) tools/bench_accuracy.py

# the loader self-builds (and caches) on first use; this just forces it
native:
	$(TEST_ENV) $(PY) -c "from kfac_tpu.utils.native_loader import _load_lib; _load_lib(); print('native/build/libkfacloader.so ok')"
