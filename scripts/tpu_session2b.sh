#!/usr/bin/env bash
# Follow-up chip session: re-runs the stages the first session2 lost to
# tunnel wedging and gives lm_large the budget its cold d1024 K-FAC
# compile needs. Keep the host core QUIET while this runs: XLA compiles
# are host-bound and a concurrent pytest run was measured to stretch
# them severalfold.
set -u
cd "$(dirname "$0")/.."
. scripts/stage_lib.sh

RUN_ID="${BENCH_RUN_ID:-$(date +%Y%m%d_%H%M%S)}"
OUT_DIR="bench_runs/tpu_session2b_${RUN_ID}"
mkdir -p "$OUT_DIR"
export BENCH_RUN_ID="$RUN_ID"
export JAX_COMPILATION_CACHE_DIR="${BENCH_JAX_CACHE:-/tmp/kfac_bench_jax_cache}"

# Compile-watch heartbeat journal (docs/OBSERVABILITY.md "Compile &
# memory truth"): every watched entry writes lowering/compiling/done
# heartbeats here with an fsync before the blocking compile, so a stage
# the tunnel (or OOM killer) takes down MID-COMPILE still leaves a
# record naming the entry it died in. Before spending any budget, read
# the verdict from the previous session's leftover journal, if any.
export KFAC_COMPILE_JOURNAL="${KFAC_COMPILE_JOURNAL:-$OUT_DIR/compile_journal.jsonl}"
for prior in bench_runs/tpu_session2b_*/compile_journal.jsonl; do
  [ -s "$prior" ] && [ "$prior" != "$KFAC_COMPILE_JOURNAL" ] || continue
  echo "prior compile journal: $prior" >&2
  timeout -k 10 60 python tools/kfac_inspect.py "$prior" >&2 || true
done

# Wait for the tunnel to recover from any prior wedge before spending
# stage budgets: sacrificial 60s probes, up to ~20 min.
for i in $(seq 1 20); do
  if timeout -k 10 60 python -c 'import jax; d=jax.devices()[0]; print("probe ok:", d.platform)' \
      >&2 2>/dev/null; then
    break
  fi
  echo "probe $i: tunnel not healthy yet" >&2
  sleep 30
done

# per-op signal first (cheapest; includes the warm-vs-cold NS refresh row)
run_stage_cmd micro_safe 400 10 "$OUT_DIR/micro_safe.jsonl" -- \
  python tools/tpu_microbench.py --sizes 512 1024 --iters 8 --rows 8192 \
    --no-pallas

run_stage resnet32_cifar    resnet resnet32_cifar     700  10
run_stage lm_large          lm     large             1500  20
run_stage lm_longctx        lm     longctx            600  20
run_stage lm_longctx_flash  lm     longctx            600  20 KFAC_TPU_PALLAS=1
run_stage resnet50_imagenet resnet resnet50_imagenet 1200  60

# time-to-target-accuracy on the vision config (north-star metric shape
# on a REAL conv net; seconds per step on-chip vs ~1s on the host CPU)
run_stage_cmd acc_vision 900 20 "$OUT_DIR/acc_vision.jsonl" -- \
  python tools/bench_accuracy.py --tasks cifar_resnet20 \
    --out "$OUT_DIR/acc_vision.md"
echo "session done: $OUT_DIR" >&2
