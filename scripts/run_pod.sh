#!/bin/bash
# Launch a kfac_tpu example trainer across every host of a TPU pod slice
# (or a SLURM/ssh CPU cluster for testing).
#
# TPU-native replacement for the reference's torchrun+ssh launcher
# (reference scripts/run_imagenet.sh): JAX runs ONE process per host, each
# seeing the host's local chips; `jax.distributed.initialize` (called by
# kfac_tpu.parallel.multihost.initialize inside the trainers) federates them
# into one global device world. On Cloud TPU the coordinator/process-count/
# process-id are auto-detected from the TPU metadata, so the launcher's only
# job is to start the same command on every worker.
#
# USAGE
#
#   Cloud TPU pod slice (run from your workstation / login VM):
#
#     $ TPU_NAME=my-v5e-64 ZONE=us-east5-a ./scripts/run_pod.sh \
#           examples/train_imagenet_resnet.py --data-dir /data/imagenet
#
#   SLURM allocation (one process per node; CPU or GPU backends):
#
#     $ sbatch -N 8 ./scripts/run_pod.sh examples/train_language_model.py
#
#   Single host (degenerates to plain python):
#
#     $ ./scripts/run_pod.sh examples/train_cifar_resnet.py --epochs 10
#
# Extra arguments are passed through to the training script verbatim.

set -euo pipefail

PRELOAD="${PRELOAD:-}"          # e.g. "source ~/venv/bin/activate ;"
PYTHON="${PYTHON:-python3}"
REPO_DIR="${REPO_DIR:-$PWD}"

if [[ $# -lt 1 ]]; then
    echo "usage: $0 <training_script.py> [args...]" >&2
    exit 2
fi
CMD="$PYTHON $*"

if [[ -n "${TPU_NAME:-}" ]]; then
    # --- Cloud TPU pod slice: fan out via the TPU VM ssh helper ---------
    # Each worker auto-discovers coordinator + process_id from metadata;
    # no rendezvous flags needed.
    echo "Launching on TPU pod ${TPU_NAME} (all workers): $CMD"
    exec gcloud compute tpus tpu-vm ssh "$TPU_NAME" \
        ${ZONE:+--zone="$ZONE"} --worker=all \
        --command="cd $REPO_DIR; $PRELOAD $CMD"
fi

# --- SLURM / nodefile clusters: one process per host ---------------------
if [[ -z "${NODEFILE:-}" && -n "${SLURM_NODELIST:-}" ]]; then
    NODEFILE=$(mktemp)
    scontrol show hostnames "$SLURM_NODELIST" > "$NODEFILE"
fi

if [[ -z "${NODEFILE:-}" ]]; then
    echo "Single host: $CMD"
    eval "$PRELOAD $CMD"
    exit $?
fi

MAIN_RANK=$(head -n 1 "$NODEFILE")
NNODES=$(wc -l < "$NODEFILE")
PORT="${COORDINATOR_PORT:-8476}"
echo "Launching on $NNODES nodes, coordinator ${MAIN_RANK}:${PORT}: $CMD"

# kfac_tpu.parallel.multihost.initialize reads these when TPU metadata is
# absent (CPU/GPU backends need explicit rendezvous, like torchrun's c10d).
RANK=0
while read -r NODE; do
    ENV="KFAC_TPU_COORDINATOR=${MAIN_RANK}:${PORT}"
    ENV+=" KFAC_TPU_NUM_PROCESSES=${NNODES} KFAC_TPU_PROCESS_ID=${RANK}"
    if [[ "$NODE" == "$(hostname)" || "$NODE" == "$(hostname -s)" ]]; then
        echo "  rank $RANK on local node $NODE"
        # subshell + export so the vars reach the trainer even when
        # PRELOAD is a compound command
        (export $ENV; eval "$PRELOAD $CMD") &
    else
        echo "  rank $RANK on remote node $NODE"
        ssh "$NODE" "cd $REPO_DIR; export $ENV; $PRELOAD $CMD" &
    fi
    RANK=$((RANK + 1))
done < "$NODEFILE"

wait
