#!/bin/bash
# One-command TPU measurement session: run the staged bench (which
# validates the Pallas kernels and measures the flagship both ways), then
# the extended microbench configs, leaving everything under bench_runs/
# and logs beside it. Run when `jax.devices()` reports a healthy TPU.
#
# The bench orchestrator handles a mid-session tunnel drop per stage
# (SIGTERM-grace watchdogs, per-run persistence), so this script never
# needs an outer kill -9 — which would wedge the tunnel.
set -u
cd "$(dirname "$0")/.."

: "${BENCH_DEADLINE_S:=2400}"
: "${BENCH_PROBE_BUDGET_S:=90}"
export BENCH_DEADLINE_S BENCH_PROBE_BUDGET_S

mkdir -p bench_runs
stamp=$(date +%Y%m%d_%H%M%S)
echo "[run_tpu_bench] bench.py (deadline ${BENCH_DEADLINE_S}s)"
python bench.py > "bench_runs/stdout_${stamp}.json" 2> "bench_runs/stderr_${stamp}.log"
rc=$?
echo "[run_tpu_bench] bench rc=${rc}"
tail -3 "bench_runs/stderr_${stamp}.log"

# extended per-op configs only if the chip is still healthy (cheap probe)
if timeout 60 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
  echo "[run_tpu_bench] extended microbench (--resnet --pipeline --head --bubble)"
  JAX_COMPILATION_CACHE_DIR=/tmp/kfac_bench_jax_cache \
    python tools/tpu_microbench.py --no-pallas --sizes 512 1024 2048 --iters 10 \
    --resnet --pipeline --head --bubble \
    > "bench_runs/micro_ext_${stamp}.jsonl" 2>> "bench_runs/stderr_${stamp}.log"
  echo "[run_tpu_bench] microbench rc=$?"
else
  echo "[run_tpu_bench] chip no longer reachable; skipping extended microbench"
fi
echo "[run_tpu_bench] results under bench_runs/ (stamp ${stamp})"
# keep-going behavior above is intentional (partials are valuable), but
# callers must still see a failed bench as a failed session
exit "$rc"
