#!/usr/bin/env bash
# Chip watcher: probe the axon tunnel on a gentle cadence; when it comes
# back, wait for any local pytest to finish (XLA compiles need the host
# core), then run the outstanding measurement stages via tpu_session2b.sh
# (which re-probes, settles between claims, and watchdogs each stage).
set -u
cd "$(dirname "$0")/.."

for i in $(seq 1 80); do   # ~6h at 4.5-minute period
  # -k 10: a wedged tunnel can leave the probe ignoring TERM inside a
  # blocked device call; KILL it so the watcher keeps polling (same
  # pattern as run_stage_cmd's `timeout -k 30`)
  if timeout -k 10 60 python -c 'import jax; jax.devices()' >/dev/null 2>&1; then
    echo "watch: tunnel healthy at probe $i ($(date +%H:%M:%S))" >&2
    while pgrep -f '[p]ytest|bench_[a]ccuracy' >/dev/null; do
      echo "watch: host-bound work running; holding stages" >&2
      sleep 60
    done
    bash scripts/tpu_session2b.sh
    exit 0
  fi
  echo "watch: probe $i down ($(date +%H:%M:%S))" >&2
  sleep 270
done
echo "watch: gave up after all probes" >&2
exit 1
