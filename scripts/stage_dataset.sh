#!/bin/bash
# Stage a dataset archive onto the local disk of every worker before
# training (reference scripts/copy_and_extract.sh equivalent).
#
# TPU pods read training data from each host's local NVMe/ssd, not a shared
# filesystem — the kfac_tpu native loader (kfac_tpu/utils/native_loader.py)
# memory-maps .npy/.npz files, so they must exist locally on every host.
#
# USAGE
#
#   Cloud TPU pod slice (fans out over all workers):
#
#     $ TPU_NAME=my-v5e-64 ZONE=us-east5-a \
#           ./scripts/stage_dataset.sh gs://bucket/imagenet.tar /tmp/imagenet
#
#   SLURM / nodefile cluster:
#
#     $ NODEFILE=$COBALT_NODEFILE \
#           ./scripts/stage_dataset.sh /lustre/imagenet.tar /tmp/imagenet
#
# The source may be a gs:// URL (fetched with gsutil on each worker) or a
# path visible from every node. Extraction is skipped when the destination
# already contains files (idempotent re-runs).

set -euo pipefail

if [[ $# -ne 2 ]]; then
    echo "usage: $0 <archive (.tar[.gz] or gs:// URL)> <dest-dir>" >&2
    exit 2
fi
SRC="$1"
DEST="$2"

# the per-worker staging command (runs on each host)
read -r -d '' STAGE <<EOF || true
set -e
if [ -d "$DEST" ] && [ -n "\$(ls -A "$DEST" 2>/dev/null)" ]; then
    echo "\$(hostname): $DEST already staged, skipping"
    exit 0
fi
mkdir -p "$DEST"
case "$SRC" in
    gs://*) gsutil -q cp "$SRC" "$DEST/_archive" ;;
    *)      cp "$SRC" "$DEST/_archive" ;;
esac
case "$SRC" in
    *.tar.gz|*.tgz) tar -xzf "$DEST/_archive" -C "$DEST" ;;
    *.tar)          tar -xf  "$DEST/_archive" -C "$DEST" ;;
    *)              mv "$DEST/_archive" "$DEST/\$(basename "$SRC")" ;;
esac
rm -f "$DEST/_archive"
echo "\$(hostname): staged $SRC -> $DEST"
EOF

if [[ -n "${TPU_NAME:-}" ]]; then
    exec gcloud compute tpus tpu-vm ssh "$TPU_NAME" \
        ${ZONE:+--zone="$ZONE"} --worker=all --command="$STAGE"
fi

if [[ -z "${NODEFILE:-}" && -n "${SLURM_NODELIST:-}" ]]; then
    NODEFILE=$(mktemp)
    scontrol show hostnames "$SLURM_NODELIST" > "$NODEFILE"
fi

if [[ -z "${NODEFILE:-}" ]]; then
    bash -c "$STAGE"
else
    while read -r NODE; do
        ssh "$NODE" "$STAGE" &
    done < "$NODEFILE"
    wait
fi
