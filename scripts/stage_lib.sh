# Shared stage runner for the one-shot chip session scripts. Source me.
#
# run_stage NAME STAGE CONFIG BUDGET_S SETTLE_S [ENV=VAL ...]
# Runs `bench.py --stage STAGE --config CONFIG` under a timeout with
# SIGTERM grace (SIGKILL mid-TPU-claim wedges the single-client tunnel
# for >30 minutes — observed 2026-07-31), settling SETTLE_S before the
# claim. Requires $OUT_DIR. Pins KFAC_TPU_PALLAS=0 unless overridden by
# a trailing ENV=VAL (last assignment wins).
run_stage() {
  local name="$1" stage="$2" config="$3" budget="$4" settle="$5"; shift 5
  run_stage_cmd "$name" "$budget" "$settle" /dev/null "$@" -- \
    python bench.py --stage "$stage" --config "$config" \
      --out "$OUT_DIR/$name.json"
}

# run_stage_cmd NAME BUDGET_S SETTLE_S STDOUT_PATH [ENV=VAL ...] -- CMD...
# The generic stage protocol: banner, settle, KFAC_TPU_PALLAS=0 default
# (trailing ENV=VAL wins), timeout with SIGTERM grace, stderr appended to
# $OUT_DIR/NAME.stderr, rc echoed.
run_stage_cmd() {
  local name="$1" budget="$2" settle="$3" stdout_path="$4"; shift 4
  local -a envs=()
  while [[ "$1" != "--" ]]; do envs+=("$1"); shift; done
  shift
  echo "=== stage $name (budget ${budget}s, pre-settle ${settle}s) ===" >&2
  sleep "$settle"
  env KFAC_TPU_PALLAS=0 ${envs[@]+"${envs[@]}"} \
    timeout -k 30 "$budget" \
    "$@" >"$stdout_path" 2>>"$OUT_DIR/$name.stderr"
  echo "=== stage $name rc=$? ===" >&2
}
