#!/bin/bash
# Kill stale JAX/python processes holding TPU chips on every worker
# (reference scripts/kill_python_procs.sh equivalent).
#
# libtpu is single-client per chip: a crashed or orphaned trainer keeps the
# chips claimed (accel lockfiles under /tmp/libtpu_lockfile) and every new
# launch hangs in backend init. Run this between failed jobs.
#
# USAGE
#
#   $ TPU_NAME=my-v5e-64 ZONE=us-east5-a ./scripts/kill_stale_jax.sh
#   $ NODEFILE=/path/to/nodes ./scripts/kill_stale_jax.sh
#   $ ./scripts/kill_stale_jax.sh            # local host only

set -uo pipefail

read -r -d '' CLEAN <<'EOF' || true
# politely TERM first (a SIGKILLed process can wedge the chip claim),
# then KILL what survives
PIDS=$(pgrep -f 'python.*(train_|kfac_tpu|jax)' | grep -v "^$$\$" || true)
if [ -n "$PIDS" ]; then
    echo "$(hostname): terminating: $PIDS"
    kill $PIDS 2>/dev/null
    sleep 5
    kill -9 $PIDS 2>/dev/null
fi
rm -f /tmp/libtpu_lockfile /tmp/tpu_logs/* 2>/dev/null
echo "$(hostname): clean"
EOF

if [[ -n "${TPU_NAME:-}" ]]; then
    exec gcloud compute tpus tpu-vm ssh "$TPU_NAME" \
        ${ZONE:+--zone="$ZONE"} --worker=all --command="$CLEAN"
fi

if [[ -z "${NODEFILE:-}" && -n "${SLURM_NODELIST:-}" ]]; then
    NODEFILE=$(mktemp)
    scontrol show hostnames "$SLURM_NODELIST" > "$NODEFILE"
fi

if [[ -z "${NODEFILE:-}" ]]; then
    bash -c "$CLEAN"
else
    while read -r NODE; do
        ssh "$NODE" "$CLEAN" &
    done < "$NODEFILE"
    wait
fi
