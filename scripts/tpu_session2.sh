#!/usr/bin/env bash
# On-chip measurement session (round 5, session 3): runs the manual bench
# stages sequentially — one JAX process at a time, the axon tunnel is
# single-client — under per-stage timeouts with SIGTERM grace.
#
# Stages, highest-value-first in case the tunnel drops mid-session:
#   1. lm_large          d1024 L8 s1024 LM  — MFU with dispatch amortized
#   2. resnet32_cifar    the reference's CIFAR config on-chip
#   3. lm_longctx        s2048 LM, default path (flash OFF)
#   4. lm_longctx_flash  s2048 LM, KFAC_TPU_PALLAS=1 (flash win regime A/B)
#   5. resnet50_imagenet the reference's ImageNet config (compile-risky: last)
set -u
cd "$(dirname "$0")/.."
. scripts/stage_lib.sh

RUN_ID="${BENCH_RUN_ID:-$(date +%Y%m%d_%H%M%S)}"
OUT_DIR="bench_runs/tpu_session2_${RUN_ID}"
mkdir -p "$OUT_DIR"
export BENCH_RUN_ID="$RUN_ID"
export JAX_COMPILATION_CACHE_DIR="${BENCH_JAX_CACHE:-/tmp/kfac_bench_jax_cache}"

run_stage lm_large          lm     large              700  5
run_stage resnet32_cifar    resnet resnet32_cifar     700  5
run_stage lm_longctx        lm     longctx            600  5
run_stage lm_longctx_flash  lm     longctx            600  5 KFAC_TPU_PALLAS=1
run_stage resnet50_imagenet resnet resnet50_imagenet  900  5
echo "session done: $OUT_DIR" >&2
